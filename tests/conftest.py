"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the same XLA partitioner runs either
way). The axon TPU plugin force-sets `jax_platforms` at import, so env vars
alone don't stick — override the config after import, before any backend
initialization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # pre-0.5 jax: the config knob doesn't exist; the XLA flag does the
    # same as long as it lands before first backend initialization
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running sweeps excluded from tier-1 "
                   "(`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "multidevice: exercises the SPMD mesh serving path on "
                   "the 8 virtual CPU devices this conftest forces; runs "
                   "in tier-1, and `-m multidevice` under "
                   "ES_TPU_DISPATCH_STRICT=1 is the sharded-grid "
                   "recompile-regression gate (see ROADMAP)")


import pytest


@pytest.fixture
def mesh_serving():
    """Force the mesh serving policy ON over the 8 virtual devices (row
    floor 1 so tiny test corpora route to the mesh), restore the
    process-wide auto policy afterwards. Yields the policy module so
    tests can read `stats()` / flip config mid-test."""
    from elasticsearch_tpu.parallel import policy
    policy.reset(full=True)
    policy.configure(enabled=True, num_shards=8, min_rows=1)
    if policy.serving_mesh() is None:
        policy.reset(full=True)
        pytest.skip("needs >= 2 jax devices (forced-host-device-count)")
    yield policy
    policy.reset(full=True)


@pytest.fixture
def mesh_serving_dp():
    """Replicated-mesh policy: (dp=2, shard=4) over the 8 virtual
    devices, row floor 1 — the dp > 1 serving grid (test_mesh_serving's
    dp cases and the strict dp-grid recompile gate)."""
    from elasticsearch_tpu.parallel import policy
    policy.reset(full=True)
    policy.configure(enabled=True, dp=2, num_shards=4, min_rows=1)
    mesh = policy.serving_mesh()
    if mesh is None or policy.dp_size() != 2:
        policy.reset(full=True)
        pytest.skip("needs 8 jax devices (forced-host-device-count)")
    yield policy
    policy.reset(full=True)


import contextlib
import socket
import subprocess


@contextlib.contextmanager
def http_server_subprocess(port: int, data_dir: str, startup_timeout=60.0):
    """Spawn a real `python -m elasticsearch_tpu.server` and wait until it
    accepts connections (shared by end-to-end client/wire tests)."""
    import time as _time

    proc = subprocess.Popen(
        [sys.executable, "-m", "elasticsearch_tpu.server", "--port",
         str(port), "--data", str(data_dir)],
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": "."},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = _time.time() + startup_timeout
    try:
        while True:
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=1).close()
                break
            except OSError:
                if _time.time() > deadline or proc.poll() is not None:
                    proc.terminate()
                    raise RuntimeError("server did not start")
                _time.sleep(0.5)
        yield proc
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.fixture(autouse=True)
def _isolate_stored_scripts():
    """GLOBAL_SCRIPTS is the process-wide cluster-state analog; clear it
    between tests so stored scripts don't leak across test cases."""
    yield
    from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
    GLOBAL_SCRIPTS.clear()
    GLOBAL_SCRIPTS._path = None
