"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the same XLA partitioner runs either
way). The axon TPU plugin force-sets `jax_platforms` at import, so env vars
alone don't stick — override the config after import, before any backend
initialization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


import pytest


@pytest.fixture(autouse=True)
def _isolate_stored_scripts():
    """GLOBAL_SCRIPTS is the process-wide cluster-state analog; clear it
    between tests so stored scripts don't leak across test cases."""
    yield
    from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
    GLOBAL_SCRIPTS.clear()
    GLOBAL_SCRIPTS._path = None
