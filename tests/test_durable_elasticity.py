"""Durable elasticity at the cluster level: block-based peer recovery
(manifest diff + chunked block fetch), node kill-and-replace without
re-ingest, live relocation with the warm-HBM handoff, and the jittered
recovery backoff / giveup policy.

These ride the deterministic multi-node harness (test_multi_node.py's
InternalTestCluster analog) so every schedule — including the backoff
jitter, which is CRC-derived rather than wall-clock — replays exactly.
"""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.cluster_node import (
    RECOVERY_START, ClusterNode,
)
from elasticsearch_tpu.cluster.state import ShardRoutingEntry
from elasticsearch_tpu.recovery import progress as rp

from tests.test_multi_node import TestCluster

DIMS = 16


def _vector_mapping():
    return {"properties": {
        "n": {"type": "long"},
        "v": {"type": "dense_vector", "dims": DIMS, "index": True,
              "similarity": "dot_product",
              "index_options": {"type": "int4_flat"}}}}


def _vec(i):
    rng = np.random.default_rng(1000 + i)
    x = rng.standard_normal(DIMS)
    return [float(f) for f in x / np.linalg.norm(x)]


def _copy_holders(c, index):
    """(primary_node_id, replica_node_id) for shard 0 of `index`."""
    primary = replica = None
    for nid, node in c.nodes.items():
        sh = node.local_shards.get((index, 0))
        if sh is None:
            continue
        if sh.routing.primary:
            primary = nid
        else:
            replica = nid
    return primary, replica


def _stop_all(c):
    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def _block_recovery_fixture(tmp_path, seed, mappings=None):
    """3-node cluster, 1 shard + 1 replica, primary flushed so a fresh
    copy CANNOT recover ops-only — phase 1 must ship blocks."""
    c = TestCluster(tmp_path, n_nodes=3, seed=seed)
    assert c.run_until(lambda: c.master() is not None
                       and len(c.master().cluster_state.nodes) == 3)
    c.any_node().client_create_index(
        "dur", settings={"index.number_of_shards": 1,
                         "index.number_of_replicas": 1},
        mappings=mappings or {"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("dur"))
    w = c.any_node()
    for i in range(30):
        doc = {"n": i}
        if mappings is not None and "v" in mappings["properties"]:
            doc["v"] = _vec(i)
        r = c.call(w.client_write, "dur",
                   {"type": "index", "id": str(i), "source": doc})
        assert r["result"] == "created"
    primary, replica = _copy_holders(c, "dur")
    pshard = c.nodes[primary].local_shards[("dur", 0)]
    pshard.engine.flush()
    assert not pshard.engine.can_replay_from(0)
    return c, primary, replica


def _replica_started_on(c, via, spare, index="dur"):
    state = c.nodes[via].cluster_state
    return any(r.node_id == spare and not r.primary
               and r.state == ShardRoutingEntry.STARTED
               for r in state.shards_of(index))


def test_block_peer_recovery_ships_blocks_and_tracks_progress(tmp_path):
    """A post-trim replica recovery runs the block path: the target's
    progress record walks INIT->BLOCKS->TRANSLOG->DONE, ships a non-zero
    block set, and the node summary (the `_nodes/stats indices.recovery`
    source) reflects it."""
    c, primary, replica = _block_recovery_fixture(tmp_path, seed=61)
    spare = next(n for n in c.nodes if n not in (primary, replica))
    c.transport.blackhole(replica)
    c.nodes[replica].stop()

    assert c.run_until(lambda: _replica_started_on(c, primary, spare),
                       max_ms=240_000), "replica never recovered on spare"

    target = c.nodes[spare]
    new_shard = target.local_shards[("dur", 0)]
    assert new_shard.engine.doc_count() == 30

    progs = [p for p in target.recoveries.values()
             if p["index"] == "dur" and p["stage"] == rp.STAGE_DONE]
    assert progs, f"no completed recovery tracked: {target.recoveries}"
    prog = progs[-1]
    assert prog["type"] == "PEER"
    assert prog["blocks_total"] > 0
    assert prog["blocks_shipped"] > 0
    assert prog["bytes_shipped"] > 0
    assert prog["source_node"] == primary
    # every shipped block landed (content-addressed) in the node cache
    assert len(target.block_cache.held()) >= prog["blocks_shipped"]

    summary = target.recovery_summary()
    assert summary["completed"] >= 1
    assert summary["blocks_shipped"] == sum(
        p["blocks_shipped"] for p in target.recoveries.values())
    assert target.recovery_stats["giveups"] == 0

    # the recovered copy keeps receiving live writes (phase 2 handoff)
    r = c.call(c.nodes[primary].client_write, "dur",
               {"type": "index", "id": "99", "source": {"n": 99}})
    assert r["result"] == "created"
    assert c.run_until(lambda: new_shard.engine.doc_count() == 31,
                       max_ms=30_000)
    _stop_all(c)


def test_primed_block_cache_skips_shipping(tmp_path):
    """The manifest diff is real: a target whose block cache already
    holds every block (here primed out-of-band, in production by an
    earlier attempt or a snapshot restore) ships NOTHING — recovery
    reuses the local copies and only replays the translog tail."""
    from elasticsearch_tpu.recovery.snapshot import collect_shard_blocks

    c, primary, replica = _block_recovery_fixture(tmp_path, seed=67)
    spare = next(n for n in c.nodes if n not in (primary, replica))

    pshard = c.nodes[primary].local_shards[("dur", 0)]
    _entries, payloads, _meta = collect_shard_blocks(
        pshard.engine, getattr(pshard, "vector_store", None))
    for digest, data in payloads.items():
        c.nodes[spare].block_cache.put(digest, data)

    c.transport.blackhole(replica)
    c.nodes[replica].stop()
    assert c.run_until(lambda: _replica_started_on(c, primary, spare),
                       max_ms=240_000), "replica never recovered on spare"

    target = c.nodes[spare]
    assert target.local_shards[("dur", 0)].engine.doc_count() == 30
    progs = [p for p in target.recoveries.values()
             if p["index"] == "dur" and p["stage"] == rp.STAGE_DONE]
    assert progs, target.recoveries
    prog = progs[-1]
    assert prog["blocks_total"] > 0
    assert prog["blocks_reused"] == prog["blocks_total"], prog
    assert prog["blocks_shipped"] == 0, \
        f"primed cache still shipped {prog['blocks_shipped']} blocks"
    assert prog["bytes_shipped"] == 0
    _stop_all(c)


def test_kill_and_replace_no_reingest_identical_results(tmp_path):
    """ISSUE acceptance: kill a copy-holding node, join a fresh one, and
    the cluster goes green again with (a) zero full re-ingests — the
    survivors' vector segment_counters stay flat and the replacement
    seeds from blocks instead of re-encoding — and (b) knn results
    byte-identical to pre-kill."""
    c, primary, replica = _block_recovery_fixture(
        tmp_path, seed=71, mappings=_vector_mapping())

    for n in c.nodes.values():
        n.refresh_all()
    q = _vec(999)
    body = {"knn": {"field": "v", "query_vector": q, "k": 5,
                    "num_candidates": 30}, "size": 5}
    before = c.call(c.any_node().client_search, "dur", dict(body))
    hits_before = [(h["_id"], h["_score"]) for h in before["hits"]["hits"]]
    assert len(hits_before) == 5

    rebuilds_before = {}
    for nid in (primary, replica):
        sh = c.nodes[nid].local_shards[("dur", 0)]
        rebuilds_before[nid] = \
            sh.vector_store.segment_counters["full_rebuilds"]

    # kill the replica holder; a brand-new node joins as its replacement
    c.transport.blackhole(replica)
    c.nodes[replica].stop()
    c.add_node("n9", tmp_path)

    def green_without_victim():
        state = c.nodes[primary].cluster_state
        shards = [s for s in state.shards_of("dur")
                  if s.node_id and s.node_id != replica]
        return len(shards) == 2 and all(
            s.state == ShardRoutingEntry.STARTED for s in shards)

    assert c.run_until(green_without_victim, max_ms=240_000), \
        "cluster never re-established both copies"

    # survivors never re-encoded, and whichever node took the new copy
    # seeded it from shipped blocks (fresh store -> rebuild counter 0)
    psh = c.nodes[primary].local_shards[("dur", 0)]
    assert psh.vector_store.segment_counters["full_rebuilds"] == \
        rebuilds_before[primary], "primary re-ingested during recovery"
    new_holder = next(
        nid for nid, n in c.nodes.items()
        if nid not in (primary, replica)
        and ("dur", 0) in n.local_shards and not n.coordinator.stopped)
    new_sh = c.nodes[new_holder].local_shards[("dur", 0)]
    assert new_sh.engine.doc_count() == 30
    assert new_sh.vector_store.segment_counters["full_rebuilds"] == 0

    for nid, n in c.nodes.items():
        if nid != replica and not n.coordinator.stopped:
            n.refresh_all()
    after = c.call(c.nodes[primary].client_search, "dur", dict(body))
    hits_after = [(h["_id"], h["_score"]) for h in after["hits"]["hits"]]
    assert hits_after == hits_before, \
        f"post-recovery results diverged:\n{hits_before}\nvs\n{hits_after}"
    _stop_all(c)


def test_relocation_recovery_warms_before_routing_flip(tmp_path):
    """Draining a node relocates its shard through the block recovery
    path; the target's progress record is typed RELOCATION and carries
    the warm-handoff report — the dispatch grid was compiled and the
    device arrays touched BEFORE the routing flip, so the first search
    on the new home never pays compile latency."""
    c = TestCluster(tmp_path, n_nodes=3, seed=73)
    assert c.run_until(lambda: c.master() is not None
                       and len(c.master().cluster_state.nodes) == 3)
    c.any_node().client_create_index(
        "move", settings={"index.number_of_shards": 1,
                          "index.number_of_replicas": 0},
        mappings=_vector_mapping())
    assert c.run_until(lambda: c.all_started("move"))
    w = c.any_node()
    for i in range(25):
        r = c.call(w.client_write, "move",
                   {"type": "index", "id": str(i),
                    "source": {"n": i, "v": _vec(i)}})
        assert r["result"] == "created"

    holder = next(nid for nid, n in c.nodes.items()
                  if ("move", 0) in n.local_shards)
    shard = c.nodes[holder].local_shards[("move", 0)]
    shard.engine.flush()  # force the block path for the relocation too

    r = c.call(c.any_node().client_update_settings,
               {"cluster.routing.allocation.exclude._name": holder})
    assert r.get("acknowledged"), r

    def moved():
        state = c.any_node().cluster_state
        shards = state.shards_of("move")
        return len(shards) == 1 \
            and shards[0].state == ShardRoutingEntry.STARTED \
            and shards[0].node_id != holder

    assert c.run_until(moved, max_ms=240_000), \
        [s.to_dict() for s in c.any_node().cluster_state.shards_of("move")]

    new_home = c.any_node().cluster_state.shards_of("move")[0].node_id
    target = c.nodes[new_home]
    assert target.local_shards[("move", 0)].engine.doc_count() == 25
    progs = [p for p in target.recoveries.values()
             if p["index"] == "move" and p["stage"] == rp.STAGE_DONE]
    assert progs, target.recoveries
    prog = progs[-1]
    assert prog["type"] == "RELOCATION"
    assert "warm" in prog, "relocation finished without the warm handoff"
    assert prog["warm"]["warmed_fields"] == ["v"]
    assert prog["warm"]["warm_nanos"] >= 0
    _stop_all(c)


def test_recovery_backoff_retries_then_gives_up(tmp_path):
    """A copy whose source keeps failing retries on the jittered
    exponential schedule (throttle time accrues, no fixed interval) and,
    at the attempt cap, reports the shard FAILED to the master instead
    of spinning forever; once the source heals, the master's reroute
    recovers the copy."""
    c, primary, replica = _block_recovery_fixture(tmp_path, seed=79)
    spare = next(n for n in c.nodes if n not in (primary, replica))
    for n in c.nodes.values():
        n._RECOVERY_RETRY_BASE_MS = 100
        n._RECOVERY_MAX_ATTEMPTS = 4

    # the recovery source now fails every RECOVERY_START deterministically
    def broken(sender, request, respond):
        raise RuntimeError("injected: source refuses recovery")

    real = c.transport._handlers[primary][RECOVERY_START]
    c.transport.register(primary, RECOVERY_START, broken)

    c.transport.blackhole(replica)
    c.nodes[replica].stop()

    target = c.nodes[spare]
    assert c.run_until(lambda: target.recovery_stats["giveups"] >= 1,
                       max_ms=240_000), \
        f"never gave up: {target.recovery_stats}"
    stats = target.recovery_stats
    assert stats["retries"] >= 3
    assert stats["attempts"] >= 4
    # the backoff wait was recorded as throttle time, and grew past the
    # fixed-interval baseline (3 retries at base would be 300ms)
    throttle = sum(p["throttle_ms"] for p in target.recoveries.values())
    assert throttle > 3 * 100, throttle

    # heal the source: the master's reroute after the failure report
    # must eventually bring the copy back green
    c.transport.register(primary, RECOVERY_START, real)
    assert c.run_until(lambda: _replica_started_on(c, primary, spare),
                       max_ms=240_000), "no recovery after heal"
    assert target.local_shards[("dur", 0)].engine.doc_count() == 30
    _stop_all(c)
