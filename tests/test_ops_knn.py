"""Unit tests for the device kNN ops (exactness vs numpy reference)."""

import numpy as np
import jax.numpy as jnp
import pytest

from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.quantization import quantize_int8, dequantize_int8
from elasticsearch_tpu.ops.topk import masked_top_k, merge_top_k, top_k

RNG = np.random.default_rng(42)


def ref_scores(queries, corpus, metric):
    q = queries.astype(np.float64)
    c = corpus.astype(np.float64)
    if metric == sim.COSINE:
        q = q / np.maximum(np.linalg.norm(q, axis=-1, keepdims=True), 1e-30)
        c = c / np.maximum(np.linalg.norm(c, axis=-1, keepdims=True), 1e-30)
        return q @ c.T
    if metric == sim.DOT_PRODUCT:
        return q @ c.T
    if metric == sim.L2_NORM:
        d = ((q[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        return -d
    raise ValueError(metric)


def recall_at_k(ids, ref_ids):
    hits = 0
    for row, ref_row in zip(ids, ref_ids):
        hits += len(set(row.tolist()) & set(ref_row.tolist()))
    return hits / ref_ids.size


@pytest.mark.parametrize("metric", [sim.COSINE, sim.DOT_PRODUCT, sim.L2_NORM])
def test_knn_exact_f32(metric):
    corpus = RNG.standard_normal((500, 32)).astype(np.float32)
    queries = RNG.standard_normal((7, 32)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=metric, dtype="f32")
    scores, ids = knn_ops.knn_search(jnp.asarray(queries), c, k=10,
                                     metric=metric, precision="f32")
    ref = ref_scores(queries, corpus, metric)
    ref_ids = np.argsort(-ref, axis=1)[:, :10]
    assert recall_at_k(np.asarray(ids), ref_ids) == 1.0
    ref_top = np.take_along_axis(ref, np.asarray(ids), axis=1)
    np.testing.assert_allclose(np.asarray(scores), ref_top, rtol=2e-4, atol=2e-4)


def test_knn_bf16_recall():
    corpus = RNG.standard_normal((2000, 64)).astype(np.float32)
    queries = RNG.standard_normal((16, 64)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.COSINE, dtype="bf16")
    _, ids = knn_ops.knn_search(jnp.asarray(queries), c, k=10, metric=sim.COSINE)
    ref = ref_scores(queries, corpus, sim.COSINE)
    ref_ids = np.argsort(-ref, axis=1)[:, :10]
    assert recall_at_k(np.asarray(ids), ref_ids) >= 0.95


def test_knn_int8_recall():
    corpus = RNG.standard_normal((2000, 64)).astype(np.float32)
    queries = RNG.standard_normal((16, 64)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.COSINE, dtype="int8")
    assert c.matrix.dtype == jnp.int8
    _, ids = knn_ops.knn_search(jnp.asarray(queries), c, k=10, metric=sim.COSINE)
    ref = ref_scores(queries, corpus, sim.COSINE)
    ref_ids = np.argsort(-ref, axis=1)[:, :10]
    assert recall_at_k(np.asarray(ids), ref_ids) >= 0.95


def test_padding_never_matches():
    corpus = RNG.standard_normal((3, 16)).astype(np.float32)  # pads to 128
    queries = RNG.standard_normal((2, 16)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.DOT_PRODUCT, dtype="f32")
    scores, ids = knn_ops.knn_search(jnp.asarray(queries), c, k=5,
                                     metric=sim.DOT_PRODUCT, precision="f32")
    ids = np.asarray(ids)
    scores = np.asarray(scores)
    # only 3 real hits; the rest must be NEG_INF sentinels
    assert (scores[:, 3:] < -1e37).all()
    assert set(ids[:, :3].flatten().tolist()) <= {0, 1, 2}


def test_filtered_knn():
    corpus = RNG.standard_normal((300, 16)).astype(np.float32)
    queries = RNG.standard_normal((4, 16)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.COSINE, dtype="f32")
    n_pad = c.matrix.shape[0]
    allowed = np.zeros(n_pad, dtype=bool)
    allowed_ids = RNG.choice(300, size=50, replace=False)
    allowed[allowed_ids] = True
    scores, ids = knn_ops.knn_search(jnp.asarray(queries), c, k=10, metric=sim.COSINE,
                                     filter_mask=jnp.asarray(allowed), precision="f32")
    assert set(np.asarray(ids).flatten().tolist()) <= set(allowed_ids.tolist())
    ref = ref_scores(queries, corpus, sim.COSINE)
    ref[:, ~allowed[:300]] = -np.inf
    ref_ids = np.argsort(-ref, axis=1)[:, :10]
    assert recall_at_k(np.asarray(ids), ref_ids) == 1.0


def test_blocked_matches_single_shot():
    corpus = RNG.standard_normal((1000, 32)).astype(np.float32)
    queries = RNG.standard_normal((5, 32)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.L2_NORM, dtype="f32", pad_to=1024)
    s1, i1 = knn_ops.knn_search(jnp.asarray(queries), c, k=10, metric=sim.L2_NORM,
                                precision="f32")
    s2, i2 = knn_ops.knn_search(jnp.asarray(queries), c, k=10, metric=sim.L2_NORM,
                                precision="f32", block_size=128)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)


def test_merge_top_k_tiebreak_by_shard():
    # two shards produce identical scores; merged ids must prefer shard 0
    s = jnp.asarray([[[1.0, 0.5]], [[1.0, 0.5]]])  # [B=2, Q=1, k=2]
    i = jnp.asarray([[[10, 11]], [[20, 21]]])
    vals, ids = merge_top_k(s, i, k=2)
    assert ids[0, 0] == 10  # shard 0 wins the tie
    assert vals[0, 0] == 1.0


def test_masked_top_k():
    scores = jnp.asarray([[5.0, 4.0, 3.0, 2.0]])
    mask = jnp.asarray([[False, True, False, True]])
    vals, ids = masked_top_k(scores, mask, k=2)
    assert ids.tolist() == [[1, 3]]
    assert vals.tolist() == [[4.0, 2.0]]


def test_quantization_roundtrip():
    m = RNG.standard_normal((64, 32)).astype(np.float32) * 5
    q, scales = quantize_int8(jnp.asarray(m))
    deq = np.asarray(dequantize_int8(q, scales, dtype=jnp.float32))
    np.testing.assert_allclose(deq, m, atol=np.abs(m).max() / 127 + 1e-6)


def test_es_score_conventions():
    raw = jnp.asarray([1.0, 0.0, -1.0])
    np.testing.assert_allclose(np.asarray(sim.to_es_score(raw, sim.COSINE)), [1.0, 0.5, 0.0])
    d2 = jnp.asarray([-0.0, -1.0, -3.0])  # raw l2 = -distance^2
    np.testing.assert_allclose(np.asarray(sim.to_es_score(d2, sim.L2_NORM)), [1.0, 0.5, 0.25])


def test_binned_kernel_interpret_mode():
    """The binned Pallas kernel runs in interpreter mode on CPU and agrees
    with the exact path (small corpus → zero bin-collision loss)."""
    from elasticsearch_tpu.ops.pallas_knn_binned import binned_knn_search, BLOCK_N
    corpus = RNG.standard_normal((BLOCK_N * 2 - 100, 32)).astype(np.float32)
    queries = RNG.standard_normal((8, 32)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.COSINE, dtype="bf16",
                             pad_to=BLOCK_N * 2)
    s_b, i_b = binned_knn_search(jnp.asarray(queries), c, k=5, interpret=True)
    s_x, i_x = knn_ops.knn_search(jnp.asarray(queries), c, k=5, metric=sim.COSINE)
    i_b, i_x = np.asarray(i_b), np.asarray(i_x)
    overlap = np.mean([len(set(i_b[r]) & set(i_x[r])) / 5 for r in range(8)])
    assert overlap >= 0.8  # bf16 ties may reorder; bulk must agree
    # ids all within valid range
    assert (i_b < BLOCK_N * 2 - 100).all() if overlap == 1.0 else True


def test_knn_search_auto_cpu_fallback():
    corpus = RNG.standard_normal((500, 16)).astype(np.float32)
    queries = RNG.standard_normal((3, 16)).astype(np.float32)
    c = knn_ops.build_corpus(corpus, metric=sim.COSINE, dtype="f32")
    s, i = knn_ops.knn_search_auto(jnp.asarray(queries), c, k=5, metric=sim.COSINE,
                                   precision="f32")
    s2, i2 = knn_ops.knn_search(jnp.asarray(queries), c, k=5, metric=sim.COSINE,
                                precision="f32")
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i2))


def test_binned_rescore_variants_interpret_mode():
    """Packed-candidate and hybrid rescore agree with (or beat) the base
    binned kernel's recall against exact f32, and only return valid rows
    (interpret-mode CPU check of the TPU recall-headroom variants)."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import pallas_knn_binned as binned
    from elasticsearch_tpu.ops import similarity as sim

    rng = np.random.default_rng(11)
    n, d, nq, k = 16384, 64, 16, 10
    centers = rng.standard_normal((256, d)).astype(np.float32) * 2.0
    vecs = centers[rng.integers(0, 256, n)] \
        + 0.7 * rng.standard_normal((n, d)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    queries = vecs[rng.integers(0, n, nq)] \
        + 0.3 * rng.standard_normal((nq, d)).astype(np.float32)
    corpus = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                                  pad_to=n)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    exact = qn @ vecs.T
    ref = np.argsort(-exact, axis=1)[:, :k]

    def recall(ids):
        ids = np.asarray(ids)
        return sum(len(set(ids[i].tolist()) & set(ref[i].tolist()))
                   for i in range(nq)) / (nq * k)

    q = jnp.asarray(queries)
    _, i0 = binned.binned_knn_search(q, corpus, k, interpret=True)
    base = recall(i0)
    for fn in (
        lambda: binned.binned_knn_search_rescored_packed(
            q, corpus, k, rescore_candidates=64, interpret=True),
        lambda: binned.binned_knn_search_rescored_hybrid(
            q, corpus, k, rescore_bins=4, rescore_candidates=64,
            interpret=True),
    ):
        s, ids = fn()
        ids = np.asarray(ids)
        assert ids.shape == (nq, k)
        assert (ids >= 0).all() and (ids < n).all()
        # rescoring may only help
        assert recall(ids) >= base - 1e-9
        # scores descend
        s = np.asarray(s)
        assert (np.diff(s, axis=1) <= 1e-5).all()


def test_int8_residual_reconstruction():
    """The optional second int8 level reconstructs rows to ~1e-4 relative
    error (vs ~1/254 for bare int8), and costs exactly one extra int8
    matrix (bf16 storage parity) that the main scan never reads."""
    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim

    rng = np.random.default_rng(3)
    vecs = rng.standard_normal((256, 32)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    c = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8")
    assert c.residual is not None and c.residual.dtype == jnp.int8
    recon = (np.asarray(c.matrix, dtype=np.float32)
             * np.asarray(c.scales)[:, None]
             + np.asarray(c.residual, dtype=np.float32)
             * np.asarray(c.residual_scales)[:, None])
    err = np.abs(recon[:256] - vecs).max()
    bare = np.abs(np.asarray(c.matrix[:256], dtype=np.float32)
                  * np.asarray(c.scales[:256])[:, None] - vecs).max()
    assert err < 1e-4
    assert err < bare / 50
    c2 = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                              residual=False)
    assert c2.residual is None


def test_auto_router_uses_residual_rescore(monkeypatch):
    """A corpus carrying the residual level routes knn_search_auto through
    the packed rescore on TPU backends (the production effect of
    index_options.rescore: true)."""
    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import pallas_knn_binned as binned
    from elasticsearch_tpu.ops import similarity as sim

    rng = np.random.default_rng(5)
    n = binned.BLOCK_N
    vecs = rng.standard_normal((n, 32)).astype(np.float32)
    c_res = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                                 pad_to=n)
    c_plain = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                                   pad_to=n, residual=False)
    calls = []
    monkeypatch.setattr(
        binned, "binned_knn_search_rescored_packed",
        lambda *a, **k: calls.append("rescored") or (None, None))
    monkeypatch.setattr(
        binned, "binned_knn_search",
        lambda *a, **k: calls.append("base") or (None, None))

    class FakeDev:
        platform = "tpu"
    monkeypatch.setattr(knn_ops.jax, "devices", lambda: [FakeDev()])
    q = jnp.asarray(rng.standard_normal((2, 32)).astype(np.float32))
    knn_ops.knn_search_auto(q, c_res, k=5, metric=sim.COSINE)
    knn_ops.knn_search_auto(q, c_plain, k=5, metric=sim.COSINE)
    assert calls == ["rescored", "base"]
