"""Layered read-path caching tier (PR 16).

Three rungs, three contracts:

1. PARITY — a cache-served response is byte-identical (modulo `took`)
   to the same body executed with the cache disabled, on the hybrid,
   kNN, and agg paths alike.
2. ZERO STALE — ingest/delete churn + refresh always invalidates: the
   key carries the reader CONTENT fingerprint, so no served response
   ever reflects a superseded snapshot.
3. CLOSED GRID — the semantic cache's probe kernel lives on the shared
   dispatch bucket ladder: a steady-state probe workload recompiles
   nothing.
"""

import json
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.search.caches import (
    LruCache, NodeCaches, RequestCache, reader_fingerprint,
    request_cache_key, value_fingerprint,
)


# ---------------------------------------------------------------------------
# unit: byte accounting, opt-in policy, key helper
# ---------------------------------------------------------------------------

class TestLruBytes:
    def test_memory_size_tracks_entries(self):
        c = LruCache(max_entries=8)
        assert c.stats()["memory_size_in_bytes"] == 0
        c.put("a", np.zeros(1024, dtype=np.float32))
        assert c.bytes >= 4096
        c.put("b", {"hits": [1, 2, 3], "s": "x" * 100})
        b2 = c.bytes
        assert b2 > 4096
        assert c.stats()["memory_size_in_bytes"] == b2

    def test_eviction_releases_bytes(self):
        c = LruCache(max_entries=2)
        c.put("a", np.zeros(256, dtype=np.float32))
        c.put("b", np.zeros(256, dtype=np.float32))
        full = c.bytes
        c.put("c", np.zeros(256, dtype=np.float32))  # evicts "a"
        assert c.stats()["evictions"] == 1
        assert c.bytes == full  # one out, one in, same size
        c.clear()
        assert c.bytes == 0

    def test_overwrite_replaces_bytes(self):
        c = LruCache(max_entries=4)
        c.put("a", np.zeros(1024, dtype=np.float32))
        c.put("a", np.zeros(16, dtype=np.float32))
        assert c.bytes < 1024


class TestOptInPolicy:
    def test_skipped_uncacheable_counts(self):
        rc = RequestCache(8)
        # opted in but non-deterministic: counted, refused
        body = {"size": 0, "request_cache": True,
                "query": {"range": {"d": {"gte": "now-1d"}}}}
        assert not rc.cacheable_tracked(body)
        assert rc.skipped_uncacheable == 1
        assert rc.stats()["skipped_uncacheable"] == 1
        # no opt-in flag: not counted (the default policy just declines)
        assert not rc.cacheable_tracked({"size": 10})
        assert rc.skipped_uncacheable == 1

    def test_device_cacheable_policy(self):
        rc = RequestCache(8)
        knn = {"size": 5, "knn": {"field": "v", "query_vector": [0.0],
                                  "k": 5}}
        assert rc.device_cacheable(knn)
        assert not rc.device_cacheable({**knn, "request_cache": False})
        assert not rc.device_cacheable({"size": 5})  # not knn-bearing
        bad = {**knn, "request_cache": True,
               "query": {"range": {"d": {"gte": "now-1h"}}}}
        assert not rc.device_cacheable(bad)
        assert rc.skipped_uncacheable == 1


class TestRequestCacheKey:
    def test_strips_cache_control_keys(self):
        fp = (("s0", 10, 10),)
        body = {"size": 0, "aggs": {"a": {"avg": {"field": "n"}}}}
        k1 = request_cache_key("plan", body, fingerprint=fp)
        k2 = request_cache_key(
            "plan", {**body, "request_cache": True, "profile": False},
            fingerprint=fp)
        assert k1 == k2

    def test_fingerprint_distinguishes(self):
        body = {"size": 0, "aggs": {"a": {"avg": {"field": "n"}}}}
        k1 = request_cache_key("plan", body,
                               fingerprint=(("s0", 10, 10),))
        k2 = request_cache_key("plan", body,
                               fingerprint=(("s0", 10, 9),))
        assert k1 != k2

    def test_vector_values_hash_as_f32(self):
        qv = [0.1, 0.2, 0.3]
        b1 = {"knn": {"field": "v", "query_vector": qv, "k": 5}}
        b2 = {"knn": {"field": "v",
                      "query_vector": np.asarray(qv, dtype=np.float32)
                      .tolist(), "k": 5}}
        assert value_fingerprint(b1) == value_fingerprint(b2)
        b3 = {"knn": {"field": "v", "query_vector": [0.1, 0.2, 0.4],
                      "k": 5}}
        assert value_fingerprint(b1) != value_fingerprint(b3)


# ---------------------------------------------------------------------------
# node-level parity + churn
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def node():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from elasticsearch_tpu.node import Node
    rng = np.random.default_rng(23)
    n = Node(tempfile.mkdtemp())
    # aggs cost-router OFF: its probe legs add wall-clock between the
    # warm/cached/off searches, which lets the background merge's
    # host-mirror flip (a different f32 reduce order) land INSIDE a
    # parity triple instead of between rounds
    n.settings["search.aggs.cost_router"] = "false"
    mappings = {"properties": {
        "body": {"type": "text"},
        "n": {"type": "long"},
        "v": {"type": "dense_vector", "dims": 8,
              "similarity": "cosine"}}}
    # "c": request-cache parity index (semantic cache OFF — its exact-
    # f32 re-rank is a deliberate, opt-in ordering refinement and would
    # muddy the byte-parity contract under test here)
    n.create_index_with_templates("c", mappings=mappings)
    # "sc": semantic cache ON, for the closed-grid test
    n.create_index_with_templates("sc", settings={
        "index.knn.semantic_cache.enabled": True,
        "index.knn.semantic_cache.size": 16,
        "index.knn.semantic_cache.threshold": 0.99,
    }, mappings=mappings)
    ops = []
    for i in range(120):
        doc = {"body": " ".join(rng.choice(list("abcdef"), 4)),
               "n": i, "v": rng.standard_normal(8).tolist()}
        ops.append({"index": {"_index": "c", "_id": str(i)}})
        ops.append(doc)
        ops.append({"index": {"_index": "sc", "_id": str(i)}})
        ops.append(doc)
    n.bulk(ops)
    n.indices.get("c").refresh()
    n.indices.get("sc").refresh()
    yield n, rng
    n.close()


def _parity(node, body):
    """Same body, cache-enabled twice vs cache-disabled; all three
    responses must agree byte-for-byte modulo took."""
    warm = node.search("c", dict(body))
    cached = node.search("c", dict(body))
    off = node.search("c", {**body, "request_cache": False})
    for r in (warm, cached, off):
        r.pop("took", None)
    assert json.dumps(warm, sort_keys=True) \
        == json.dumps(cached, sort_keys=True)
    assert json.dumps(cached, sort_keys=True) \
        == json.dumps(off, sort_keys=True)
    return cached


class TestNodeParityAndChurn:
    def test_agg_parity_and_hit(self, node):
        n, _ = node
        before = n.caches.request.hits
        body = {"size": 0, "aggs": {"s": {"sum": {"field": "n"}}}}
        _parity(n, body)
        assert n.caches.request.hits > before

    def test_knn_parity_and_hit(self, node):
        n, rng = node
        body = {"size": 5, "request_cache": True,
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 5, "num_candidates": 20}}
        before = n.caches.device_request.hits
        _parity(n, body)
        assert n.caches.device_request.hits > before

    def test_zero_stale_across_churn(self, node):
        n, rng = node
        agg = {"size": 0, "aggs": {"s": {"sum": {"field": "n"}}}}
        knn = {"size": 3,
               "knn": {"field": "v",
                       "query_vector": rng.standard_normal(8).tolist(),
                       "k": 3, "num_candidates": 20}}
        for round_no in range(3):
            a = _parity(n, agg)
            k = _parity(n, knn)
            # churn: one ingest + one delete, then refresh
            doc_id = f"churn{round_no}"
            n.index_doc("c", doc_id, {
                "body": "zz", "n": 100000 + round_no,
                "v": rng.standard_normal(8).tolist()})
            victim = k["hits"]["hits"][0]["_id"]
            n.delete_doc("c", victim)
            n.indices.get("c").refresh()
            # the cached agg/knn MUST reflect the churn (fingerprint
            # moved): sum changed, deleted doc gone
            a2 = _parity(n, agg)
            k2 = _parity(n, knn)
            assert a2["aggregations"]["s"]["value"] \
                != a["aggregations"]["s"]["value"]
            assert victim not in [h["_id"] for h in k2["hits"]["hits"]]

    def test_hybrid_parity_and_hit(self, node):
        n, rng = node
        body = {"rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 40}},
                "query": {"match": {"body": "a b"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 40, "num_candidates": 40},
                "size": 10}
        warm = n.search("c", dict(body))
        before = n.local_node_stats()["indices"]["hybrid"][
            "request_cache_hits"]
        cached = n.search("c", dict(body))
        assert n.local_node_stats()["indices"]["hybrid"][
            "request_cache_hits"] == before + 1
        off = n.search("c", {**body, "request_cache": False})
        for r in (warm, cached, off):
            r.pop("took", None)
        assert json.dumps(warm, sort_keys=True) \
            == json.dumps(cached, sort_keys=True)
        assert json.dumps(cached, sort_keys=True) \
            == json.dumps(off, sort_keys=True)

    def test_profile_annotation_and_bypass(self, node):
        n, _ = node
        body = {"size": 0, "profile": True,
                "aggs": {"s": {"sum": {"field": "n"}}}}
        r1 = n.search("c", dict(body))
        shard_prof = r1["profile"]["shards"][0]
        assert shard_prof["cache"]["rung"] == "shard_request"
        r2 = n.search("c", dict(body))
        assert r2["profile"]["shards"][0]["cache"]["hit"] is True

    def test_stats_report_real_bytes(self, node):
        n, _ = node
        n.search("c", {"size": 0,
                       "aggs": {"s": {"sum": {"field": "n"}}}})
        st = n.local_node_stats()["indices"]
        rc = st["request_cache"]
        assert rc["memory_size_in_bytes"] > 0
        assert rc["hit_count"] + rc["miss_count"] > 0
        assert "skipped_uncacheable" in rc
        assert rc["host"]["memory_size_in_bytes"] >= 0
        assert rc["device"]["memory_size_in_bytes"] >= 0


# ---------------------------------------------------------------------------
# semantic cache: guard + closed grid
# ---------------------------------------------------------------------------

class _FakeSource:
    def __init__(self, arr):
        self.arr = np.asarray(arr, dtype=np.float32)
        self.dims = self.arr.shape[1]

    def gather(self, pos):
        return self.arr[np.asarray(pos, dtype=np.int64)]


class _FakeFc:
    def __init__(self, docs):
        self.source = _FakeSource(docs)
        self.row_map = np.arange(len(docs), dtype=np.int64)
        self.dims = docs.shape[1]
        self.gens = None


def _fill(cache, fc, q, k):
    """Insert one exact top-k window for q (computed in f32)."""
    from elasticsearch_tpu.quant.rescore import exact_scores
    scores = exact_scores(q[None, :], fc.source.arr[None], sim.COSINE)[0]
    top = np.argsort(-scores, kind="stable")[:k]
    cache.insert_many(
        [(q, None)], [(top.astype(np.int64), scores[top])],
        fc, k, "bf16", None)


class TestSemanticGuard:
    DIMS = 8

    def _mk(self, threshold=0.99, seed=5, n_docs=64):
        from elasticsearch_tpu.vectors.semantic_cache import SemanticCache
        rng = np.random.default_rng(seed)
        docs = rng.standard_normal((n_docs, self.DIMS)).astype(np.float32)
        fc = _FakeFc(docs)
        cache = SemanticCache(16, threshold, self.DIMS, sim.COSINE,
                              version=("t",))
        return cache, fc, rng

    def _drift(self, q, target_sim, rng):
        """A query at a controlled cosine distance from q."""
        qn = q / np.linalg.norm(q)
        r = rng.standard_normal(self.DIMS).astype(np.float32)
        r -= (r @ qn) * qn
        r /= np.linalg.norm(r)
        out = target_sim * qn + np.sqrt(1 - target_sim ** 2) * r
        return out.astype(np.float32)

    def test_identical_resend_serves_exact_topk(self):
        cache, fc, rng = self._mk()
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        served, stats = cache.probe([(q, None)], 5, "bf16", None)
        assert stats == {"probed": 1, "hits": 1, "rejects": 0,
                         "nanos": stats["nanos"]}
        rows, scores = served[0]
        from elasticsearch_tpu.quant.rescore import exact_scores
        exact = exact_scores(q[None, :], fc.source.arr[None],
                             sim.COSINE)[0]
        expect = np.argsort(-exact, kind="stable")[:5]
        assert np.array_equal(rows, expect)
        assert np.allclose(scores, exact[expect])

    def test_rescore_guard_rejects_unprovable_drift(self):
        """A near-duplicate ABOVE the probe threshold still rejects when
        the rescored k-th score cannot dominate the window floor plus
        the drift bound: with window == k the rescored k-th IS the
        floor, so any real drift margin fails the dominance check."""
        cache, fc, rng = self._mk(threshold=0.99)
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        q_near = self._drift(q, 0.995, rng)  # above threshold
        served, stats = cache.probe([(q_near, None)], 5, "bf16", None)
        assert served == {}
        assert stats["rejects"] == 1 and stats["hits"] == 0

    def test_below_threshold_is_a_plain_miss(self):
        cache, fc, rng = self._mk(threshold=0.99)
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        q_far = self._drift(q, 0.5, rng)
        served, stats = cache.probe([(q_far, None)], 5, "bf16", None)
        assert served == {} and stats["rejects"] == 0

    def test_filtered_queries_bypass(self):
        cache, fc, rng = self._mk()
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        served, stats = cache.probe(
            [(q, np.array([1, 2, 3], dtype=np.int64))], 5, "bf16", None)
        assert served == {} and stats["probed"] == 0

    def test_k_mismatch_never_serves(self):
        cache, fc, rng = self._mk()
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        served, stats = cache.probe([(q, None)], 10, "bf16", None)
        assert served == {} and stats["rejects"] == 1

    def test_complete_window_serves_any_near_dup(self):
        """k >= corpus: the window IS the corpus, nothing exists outside
        it, so any above-threshold neighbor serves (exact re-rank)."""
        cache, fc, rng = self._mk(threshold=0.99, n_docs=4)
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=8)  # k > n_docs -> complete
        q_near = self._drift(q, 0.995, rng)
        served, stats = cache.probe([(q_near, None)], 8, "bf16", None)
        assert stats["hits"] == 1
        rows, scores = served[0]
        from elasticsearch_tpu.quant.rescore import exact_scores
        exact = exact_scores(q_near[None, :], fc.source.arr[None],
                             sim.COSINE)[0]
        expect = np.argsort(-exact, kind="stable")[:8]
        assert np.array_equal(rows, expect)

    def test_memory_size(self):
        cache, fc, rng = self._mk()
        empty = cache.memory_size_in_bytes()
        q = rng.standard_normal(self.DIMS).astype(np.float32)
        _fill(cache, fc, q, k=5)
        assert cache.memory_size_in_bytes() > empty
        assert cache.entry_count() == 1


class TestSemanticClosedGrid:
    def test_second_pass_compiles_nothing(self, node):
        """Steady-state semcache probing stays on the compiled grid: after
        one warmup pass (ring upload + probe + miss dispatch), a second
        pass of probes — hits, rejects, and misses alike — records ZERO
        new compiles."""
        n, rng = node
        base = rng.standard_normal(8).astype(np.float32)

        def drive(qs):
            for q in qs:
                n.search("sc", {
                    "size": 3, "request_cache": False,
                    "knn": {"field": "v", "query_vector": q.tolist(),
                            "k": 3, "num_candidates": 20}})

        warm = [base, base + 1e-6, rng.standard_normal(8)]
        drive([q.astype(np.float32) for q in warm])
        st = n.local_node_stats()["indices"]["knn"]
        assert st["semantic_probes"] > 0
        before = dispatch.DISPATCH.compile_count()
        drive([base, (base + 1e-6).astype(np.float32),
               rng.standard_normal(8).astype(np.float32)])
        after = dispatch.DISPATCH.compile_count()
        assert after == before, (
            f"semcache steady state recompiled {after - before} "
            f"programs; stats={dispatch.stats(per_bucket=True)}")
