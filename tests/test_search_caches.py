"""Shard request cache, node query cache, can_match pre-filter
(IndicesRequestCache / IndicesQueryCache / CanMatchPreFilterSearchPhase
analogs)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.analysis import AnalysisRegistry
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.search.caches import (
    NodeCaches, QueryCache, RequestCache, can_match, field_stats,
)
from elasticsearch_tpu.search.service import execute_query_phase

MAPPINGS = {"properties": {"n": {"type": "long"},
                           "title": {"type": "text"},
                           "tag": {"type": "keyword"}}}


@pytest.fixture
def engine(tmp_path):
    mapper = MapperService(MAPPINGS, registry=AnalysisRegistry())
    eng = Engine(str(tmp_path / "s0"), mapper, translog_sync="async")
    for i in range(20):
        eng.index(str(i), {"n": i, "title": f"doc {i}",
                           "tag": "even" if i % 2 == 0 else "odd"})
    eng.refresh()
    yield eng, mapper
    eng.close()


def test_request_cache_policy():
    assert RequestCache.cacheable({"size": 0, "aggs": {"a": {"avg": {"field": "n"}}}})
    assert not RequestCache.cacheable({"size": 10})
    assert not RequestCache.cacheable({})  # default size=10
    assert RequestCache.cacheable({"size": 10, "request_cache": True})
    assert not RequestCache.cacheable({"size": 0, "request_cache": False})
    # non-deterministic requests never cache
    assert not RequestCache.cacheable(
        {"size": 0, "query": {"range": {"d": {"gte": "now-1d"}}}})
    assert not RequestCache.cacheable(
        {"size": 0, "query": {"script_score": {"script": "x"}}})


def test_request_cache_hit_and_reader_gen_invalidation(engine):
    eng, mapper = engine
    caches = NodeCaches()
    body = {"size": 0, "aggs": {"s": {"sum": {"field": "n"}}}}
    reader = eng.acquire_searcher()
    key = caches.request.key("idx", reader.gen, body)
    assert caches.request.get(key) is None
    result = execute_query_phase(reader, mapper, body)
    caches.request.put(key, result)
    assert caches.request.get(key) is result
    assert caches.request.hits == 1

    # a refresh that changed the shard produces a new reader gen -> miss
    eng.index("new", {"n": 100, "title": "doc new", "tag": "odd"})
    reader2 = eng.refresh()
    assert reader2.gen != reader.gen
    assert caches.request.get(caches.request.key("idx", reader2.gen, body)) is None


def test_request_cache_key_order_insensitive():
    rc = RequestCache()
    k1 = rc.key("idx", 1, {"aggs": {"a": 1}, "size": 0})
    k2 = rc.key("idx", 1, {"size": 0, "aggs": {"a": 1}})
    assert k1 == k2
    # request_cache flag itself is not part of the key
    k3 = rc.key("idx", 1, {"size": 0, "aggs": {"a": 1}, "request_cache": True})
    assert k1 == k3


def test_query_cache_caches_filter_rows(engine):
    eng, mapper = engine
    cache = QueryCache()
    reader = eng.acquire_searcher()
    body = {"query": {"bool": {"filter": [{"term": {"tag": "even"}}],
                               "must": [{"match": {"title": "doc"}}]}},
            "size": 20}
    r1 = execute_query_phase(reader, mapper, body, query_cache=cache)
    assert cache.misses >= 1 and cache.hits == 0
    r2 = execute_query_phase(reader, mapper, body, query_cache=cache)
    assert cache.hits >= 1
    assert np.array_equal(r1.rows, r2.rows)
    assert r1.total_hits == r2.total_hits == 10


def test_query_cache_lru_eviction():
    c = QueryCache(max_entries=2)
    c.put_rows(1, "a", np.array([1]))
    c.put_rows(1, "b", np.array([2]))
    c.put_rows(1, "c", np.array([3]))
    assert c.evictions == 1
    assert c.get_rows(1, "a") is None  # oldest evicted


# ---------------------------------------------------------------- can_match

def test_field_stats(engine):
    eng, mapper = engine
    reader = eng.acquire_searcher()
    assert field_stats(reader, "n") == (0.0, 19.0)
    assert field_stats(reader, "absent") is None
    # deletes narrow the live range
    eng.delete("19")
    reader2 = eng.refresh()
    assert field_stats(reader2, "n") == (0.0, 18.0)


def test_can_match_range_pruning(engine):
    eng, mapper = engine
    reader = eng.acquire_searcher()
    hit = {"query": {"range": {"n": {"gte": 5, "lte": 10}}}}
    miss_above = {"query": {"range": {"n": {"gte": 100}}}}
    miss_below = {"query": {"range": {"n": {"lt": 0}}}}
    boundary = {"query": {"range": {"n": {"gte": 19}}}}
    gt_boundary = {"query": {"range": {"n": {"gt": 19}}}}
    assert can_match(reader, mapper, hit)
    assert not can_match(reader, mapper, miss_above)
    assert not can_match(reader, mapper, miss_below)
    assert can_match(reader, mapper, boundary)
    assert not can_match(reader, mapper, gt_boundary)
    # ranges under bool.filter constrain too
    assert not can_match(reader, mapper, {"query": {"bool": {"filter": [
        {"range": {"n": {"gte": 100}}}]}}})
    # should-clause ranges do NOT constrain (conservative)
    assert can_match(reader, mapper, {"query": {"bool": {"should": [
        {"range": {"n": {"gte": 100}}}]}}})
    # no range at all -> always might match
    assert can_match(reader, mapper, {"query": {"match_all": {}}})
    # a required range on a field this shard has never seen cannot match
    assert not can_match(reader, mapper,
                         {"query": {"range": {"absent": {"gte": 1}}}})
