"""Painless interpreter: language surface, sandbox, and script contexts
(modules/lang-painless analog; elasticsearch_tpu/script/painless.py)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.script.painless import (
    PainlessError, compile_painless, execute,
)


def run(src, **bindings):
    return execute(compile_painless(src), bindings)


# ------------------------------------------------------------------ language

def test_arithmetic_and_implicit_return():
    assert run("1 + 2 * 3") == 7
    assert run("(1 + 2) * 3.0") == 9.0
    assert run("7 % 3") == 1
    assert run("'a' + 'b' + 1") == "ab1"


def test_java_integer_division_truncates_toward_zero():
    assert run("7 / 2") == 3
    assert run("-7 / 2") == -3
    assert run("7.0 / 2") == 3.5


def test_variables_and_compound_assignment():
    assert run("def x = 4; x += 3; x *= 2; return x;") == 14
    assert run("int a = 1; int b = 2; def c = a + b; c") == 3


def test_if_else_chain():
    src = """
    def grade(int n) {
      if (n >= 90) { return 'A'; }
      else if (n >= 80) { return 'B'; }
      else { return 'C'; }
    }
    return grade(params.n);
    """
    assert run(src, params={"n": 95}) == "A"
    assert run(src, params={"n": 85}) == "B"
    assert run(src, params={"n": 10}) == "C"


def test_for_loop_and_while():
    assert run("def s = 0; for (int i = 0; i < 10; i++) { s += i; } return s;") == 45
    assert run("def s = 0; def i = 0; while (i < 5) { s += i; i++; } s") == 10
    assert run("def i = 0; do { i++; } while (i < 3); i") == 3


def test_foreach_over_list_and_map():
    assert run("def s = 0; for (def x : params.xs) { s += x; } s",
               params={"xs": [1, 2, 3]}) == 6
    assert run("def n = 0; for (k in params.m) { n += params.m[k]; } n",
               params={"m": {"a": 1, "b": 2}}) == 3


def test_break_continue():
    src = """
    def s = 0;
    for (int i = 0; i < 100; i++) {
      if (i % 2 == 0) { continue; }
      if (i > 7) { break; }
      s += i;
    }
    return s;
    """
    assert run(src) == 1 + 3 + 5 + 7


def test_user_functions_and_recursion():
    src = """
    int fib(int n) { if (n < 2) { return n; } return fib(n-1) + fib(n-2); }
    return fib(10);
    """
    assert run(src) == 55


def test_list_and_map_literals_and_methods():
    assert run("def l = [1, 2, 3]; l.add(4); return l.size();") == 4
    assert run("def m = ['a': 1]; m.put('b', 2); return m.get('b');") == 2
    assert run("def m = [:]; m.x = 5; return m.x;") == 5
    assert run("def l = new ArrayList(); l.add('q'); l.contains('q')") is True
    assert run("def m = new HashMap(); m.containsKey('nope')") is False


def test_string_methods():
    assert run("'hello'.substring(1, 3)") == "el"
    assert run("'Hello'.toLowerCase()") == "hello"
    assert run("'a,b,c'.split(',').length") == 3
    assert run("'abc'.length()") == 3


def test_ternary_and_elvis():
    assert run("params.x > 3 ? 'big' : 'small'", params={"x": 5}) == "big"
    assert run("params.missing ?: 'default'", params={}) == "default"


def test_math_and_statics():
    assert run("Math.max(3, Math.abs(-7))") == 7
    assert run("Integer.parseInt('42') + 1") == 43
    assert run("(int) 3.9") == 3


def test_instanceof():
    assert run("params.x instanceof String", params={"x": "s"}) is True
    assert run("params.x instanceof List", params={"x": [1]}) is True
    assert run("params.x instanceof Map", params={"x": 3}) is False


# ------------------------------------------------------------------- sandbox

def test_unknown_variable_rejected():
    with pytest.raises(IllegalArgumentError):
        run("__import__('os')")


def test_unknown_method_rejected():
    with pytest.raises(IllegalArgumentError):
        run("'s'.__class__()")
    with pytest.raises(IllegalArgumentError):
        run("params.getClass()", params={})


def test_unknown_constructor_rejected():
    with pytest.raises(IllegalArgumentError):
        run("new File('/etc/passwd')")


def test_infinite_loop_budget():
    with pytest.raises(IllegalArgumentError, match="loop iteration budget"):
        run("def i = 0; while (true) { i += 1; } i")


def test_recursion_depth_capped():
    with pytest.raises(IllegalArgumentError, match="call depth"):
        run("int f(int n) { return f(n + 1); } return f(0);")


def test_syntax_error_reported():
    with pytest.raises(PainlessError):
        compile_painless("def x = ;")


# ----------------------------------------------------------- script contexts

@pytest.fixture
def scoring_ctx(tmp_path):
    from elasticsearch_tpu.index.analysis import AnalysisRegistry
    from elasticsearch_tpu.index.engine import Engine
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.search.queries import SearchContext

    mapper = MapperService({"properties": {"n": {"type": "long"},
                                           "tags": {"type": "keyword"}}},
                           registry=AnalysisRegistry())
    eng = Engine(str(tmp_path / "s"), mapper, translog_sync="async")
    for i in range(6):
        eng.index(str(i), {"n": i, "tags": ["even" if i % 2 == 0 else "odd"]})
    reader = eng.refresh()
    yield SearchContext(reader, mapper), reader
    eng.close()


def test_statement_script_score(scoring_ctx):
    from elasticsearch_tpu.search.script_score import Script
    ctx, reader = scoring_ctx
    rows = np.arange(6, dtype=np.int64)
    base = np.ones(6, dtype=np.float32)
    script = Script({"source": """
        def v = doc['n'].value;
        if (v % 2 == 0) { return v * 10; }
        return v;
    """})
    out = script.evaluate(ctx, rows, base)
    assert list(out) == [0.0, 1.0, 20.0, 3.0, 40.0, 5.0]


def test_statement_script_with_loop_over_doc_values(scoring_ctx):
    from elasticsearch_tpu.search.script_score import Script
    ctx, reader = scoring_ctx
    rows = np.arange(6, dtype=np.int64)
    script = Script({"source": """
        def total = 0;
        for (def t : doc['tags'].values) {
          if (t == 'even') { total += 100; }
        }
        return total + doc['n'].value;
    """})
    out = script.evaluate(ctx, rows, np.zeros(6, dtype=np.float32))
    assert list(out) == [100.0, 1.0, 102.0, 3.0, 104.0, 5.0]


def test_expression_fast_path_still_vectorized(scoring_ctx):
    from elasticsearch_tpu.search.script_score import Script
    ctx, reader = scoring_ctx
    script = Script({"source": "doc['n'].value * 2 + _score"})
    assert script.tree is not None  # batched numpy path
    out = script.evaluate(ctx, np.arange(6, dtype=np.int64),
                          np.ones(6, dtype=np.float32))
    assert list(out) == [1.0, 3.0, 5.0, 7.0, 9.0, 11.0]


def test_update_script_with_loops_and_ctx(tmp_path):
    from elasticsearch_tpu.node import Node
    node = Node(str(tmp_path / "d"))
    node.index_doc("t", "1", {"counts": [1, 2, 3], "total": 0})
    node.update_doc("t", "1", {"script": {"source": """
        ctx._source.total = 0;
        for (def c : ctx._source.counts) { ctx._source.total += c; }
        ctx._source.tag = params.tag;
    """, "params": {"tag": "summed"}}})
    doc = node.get_doc("t", "1")
    assert doc["_source"]["total"] == 6
    assert doc["_source"]["tag"] == "summed"
    node.close()


def test_update_script_ctx_op_none_and_delete(tmp_path):
    from elasticsearch_tpu.node import Node
    node = Node(str(tmp_path / "d2"))
    node.index_doc("t", "1", {"stale": False, "n": 1})
    node.index_doc("t", "2", {"stale": True, "n": 2})

    # ctx.op = 'none' -> noop, document untouched
    r = node.update_doc("t", "1", {"script": {"source":
        "if (ctx._source.stale == false) { ctx.op = 'none' } "
        "else { ctx._source.n += 1 }"}})
    assert r["result"] == "noop"
    assert node.get_doc("t", "1")["_source"]["n"] == 1

    # ctx.op = 'delete' -> document removed
    r = node.update_doc("t", "2", {"script": {"source":
        "if (ctx._source.stale) { ctx.op = 'delete' }"}})
    assert r["result"] == "deleted"
    assert not node.get_doc("t", "2")["found"]
    node.close()


def test_null_arithmetic_is_client_error(tmp_path):
    from elasticsearch_tpu.node import Node
    node = Node(str(tmp_path / "d3"))
    node.index_doc("t", "1", {"a": 1})
    with pytest.raises(IllegalArgumentError):
        node.update_doc("t", "1", {"script": {"source":
            "ctx._source.missing += 1"}})
    node.close()
