"""ML anomaly-detection jobs: native sidecar process, job lifecycle,
datafeeds, results (reference: x-pack/plugin/ml + elastic/ml-cpp processes
managed via NativeController/ProcessPipes — SURVEY.md §2.9, §2.11)."""

import json

import pytest

from elasticsearch_tpu.ml.process import (
    AutodetectProcess,
    PyAutodetect,
    autodetect_binary,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = b""
        if body is not None:
            if isinstance(body, (list, tuple)):
                raw = b"\n".join(json.dumps(l).encode() for l in body) + b"\n"
            else:
                raw = json.dumps(body).encode()
        q = {k: str(v) for k, v in query.items()}
        return self.rc.dispatch(method, path, q, raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


JOB = {
    "analysis_config": {
        "bucket_span": "60s",
        "detectors": [{"function": "mean", "field_name": "responsetime",
                       "partition_field_name": "airline"}],
    },
    "data_description": {"time_field": "time"},
}


def _records(n_buckets=30, anomaly_bucket=None, value=10.0, anomaly_value=500.0):
    recs = []
    for b in range(n_buckets):
        for i in range(10):
            v = anomaly_value if b == anomaly_bucket else value + (i % 3) * 0.5
            recs.append({"time": b * 60 + i * 5, "responsetime": v,
                         "airline": "AAL"})
    return recs


def test_native_binary_builds():
    # the C++ toolchain is in the image; the sidecar must actually build
    assert autodetect_binary() is not None


def test_process_detects_injected_anomaly():
    results = []
    proc = AutodetectProcess({"job_id": "j", **JOB}, results.append)
    assert proc.is_native
    for r in _records(30, anomaly_bucket=25):
        proc.write_record(r["time"], r)
    ack = proc.flush("f1")
    assert ack["id"] == "f1"
    proc.close()
    buckets = [m for m in results if m["type"] == "bucket"]
    assert len(buckets) == 30
    anomalous = [b for b in buckets if b["anomaly_score"] > 50]
    assert [b["timestamp"] for b in anomalous] == [25 * 60 * 1000]
    recs = [m for m in results if m["type"] == "record"]
    big = [r for r in recs if r["record_score"] > 50]
    assert big and big[0]["partition_field_value"] == "AAL"
    assert big[0]["actual"][0] == 500.0
    assert abs(big[0]["typical"][0] - 10.5) < 1.0


def test_python_fallback_matches_native_semantics():
    """PyAutodetect is the no-compiler fallback; its scores must agree with
    the native process on the same stream."""
    native_out, py_out = [], []
    proc = AutodetectProcess({"job_id": "j", **JOB}, native_out.append)
    py = PyAutodetect({"job_id": "j", **JOB}, py_out.append)
    for r in _records(20, anomaly_bucket=15):
        proc.write_record(r["time"], r)
        py.handle({"type": "record", "time": r["time"], "fields": r})
    proc.flush()
    py.handle({"type": "flush", "id": "f"})
    proc.close()
    nb = {m["timestamp"]: m["anomaly_score"] for m in native_out
          if m["type"] == "bucket"}
    pb = {m["timestamp"]: m["anomaly_score"] for m in py_out
          if m["type"] == "bucket"}
    assert set(nb) == set(pb)
    for ts in nb:
        assert abs(nb[ts] - pb[ts]) < 1e-6, ts


def test_job_lifecycle_and_results(client):
    status, job = client.req("PUT", "/_ml/anomaly_detectors/latency", JOB)
    assert status == 200 and job["job_id"] == "latency"
    assert job["state"] == "closed"

    status, _ = client.req("POST", "/_ml/anomaly_detectors/latency/_open")
    assert status == 200

    status, counts = client.req("POST", "/_ml/anomaly_detectors/latency/_data",
                                _records(30, anomaly_bucket=25))
    assert status == 202
    assert counts["processed_record_count"] == 300

    status, flush = client.req("POST",
                               "/_ml/anomaly_detectors/latency/_flush")
    assert status == 200 and flush["flushed"]

    status, res = client.req(
        "GET", "/_ml/anomaly_detectors/latency/results/buckets",
        {"anomaly_score": 50})
    assert status == 200 and res["count"] == 1
    assert res["buckets"][0]["timestamp"] == 25 * 60 * 1000

    status, res = client.req(
        "GET", "/_ml/anomaly_detectors/latency/results/records",
        {"record_score": 50})
    assert res["count"] >= 1
    rec = res["records"][0]
    assert rec["function"] == "mean" and rec["field_name"] == "responsetime"

    status, stats = client.req("GET",
                               "/_ml/anomaly_detectors/latency/_stats")
    assert stats["jobs"][0]["state"] == "opened"
    assert stats["jobs"][0]["data_counts"]["processed_record_count"] == 300

    status, _ = client.req("POST", "/_ml/anomaly_detectors/latency/_close")
    assert status == 200
    status, stats = client.req("GET",
                               "/_ml/anomaly_detectors/latency/_stats")
    assert stats["jobs"][0]["state"] == "closed"

    # results survive close; queryable via the plain search API too
    status, res = client.req("POST", "/.ml-anomalies-shared/_search",
                             {"query": {"term": {"result_type": "bucket"}},
                              "size": 0})
    assert res["hits"]["total"]["value"] == 30


def test_model_state_persists_across_close_open(node):
    """Closing persists model state; reopening restores it (the baseline
    learned before close still flags anomalies after reopen)."""
    node.ml.put_job("j1", JOB)
    node.ml.open_job("j1")
    node.ml.post_data("j1", _records(20))
    node.ml.close_job("j1")

    node.ml.open_job("j1")
    # continue the stream where it left off, with an anomaly right away
    recs = [{"time": 20 * 60 + i * 5, "responsetime": 500.0, "airline": "AAL"}
            for i in range(10)]
    recs += [{"time": 21 * 60 + i * 5, "responsetime": 10.0, "airline": "AAL"}
             for i in range(10)]
    node.ml.post_data("j1", recs)
    node.ml.flush_job("j1")
    res = node.ml.get_buckets("j1", {"anomaly_score": 50})
    assert res["count"] == 1
    assert res["buckets"][0]["timestamp"] == 20 * 60 * 1000
    node.ml.close_job("j1")


def test_count_detector_and_validation(node):
    node.ml.put_job("c1", {"analysis_config": {
        "bucket_span": 60, "detectors": [{"function": "count"}]},
        "data_description": {"time_field": "t"}})
    node.ml.open_job("c1")
    recs = []
    for b in range(20):
        n = 100 if b == 15 else 5  # count spike
        recs += [{"t": b * 60 + (i % 60)} for i in range(n)]
    node.ml.post_data("c1", recs)
    node.ml.flush_job("c1")
    res = node.ml.get_buckets("c1", {"anomaly_score": 50})
    assert [b["timestamp"] for b in res["buckets"]] == [15 * 60 * 1000]
    node.ml.close_job("c1")

    from elasticsearch_tpu.common.errors import ValidationError
    with pytest.raises(ValidationError):
        node.ml.put_job("bad", {"analysis_config": {
            "detectors": [{"function": "mean"}]}})  # mean needs field_name
    with pytest.raises(ValidationError):
        node.ml.put_job("bad", {"analysis_config": {
            "detectors": [{"function": "rare"}]}})  # rare needs by_field


def test_rare_detector(node):
    node.ml.put_job("r1", {"analysis_config": {
        "bucket_span": 60,
        "detectors": [{"function": "rare", "by_field_name": "status"}]},
        "data_description": {"time_field": "t"}})
    node.ml.open_job("r1")
    recs = []
    for b in range(30):
        for i in range(10):
            recs.append({"t": b * 60 + i, "status": "200"})
        if b == 25:
            recs.append({"t": b * 60 + 30, "status": "500"})  # rare value
    node.ml.post_data("r1", recs)
    node.ml.flush_job("r1")
    res = node.ml.get_records("r1", {"record_score": 10})
    assert res["count"] >= 1
    assert res["records"][0]["by_field_value"] == "500"
    node.ml.close_job("r1")


def test_datafeed_from_index(client, node):
    # index source data with an ISO time field
    ops = []
    for b in range(25):
        for i in range(5):
            v = 400.0 if b == 20 else 10.0
            ops.append({"index": {"_index": "metrics"}})
            ops.append({"time": (b * 60 + i * 10) * 1000, "cpu": v})
    client.req("POST", "/_bulk", ops, refresh="true")

    status, _ = client.req("PUT", "/_ml/anomaly_detectors/cpu-job", {
        "analysis_config": {"bucket_span": "60s",
                            "detectors": [{"function": "mean",
                                           "field_name": "cpu"}]},
        "data_description": {"time_field": "time", "time_format": "epoch_ms"},
    })
    assert status == 200
    status, df = client.req("PUT", "/_ml/datafeeds/cpu-feed",
                            {"job_id": "cpu-job", "indices": ["metrics"]})
    assert status == 200 and df["datafeed_id"] == "cpu-feed"

    status, preview = client.req("GET", "/_ml/datafeeds/cpu-feed/_preview")
    assert status == 200 and len(preview) > 0

    client.req("POST", "/_ml/anomaly_detectors/cpu-job/_open")
    status, started = client.req("POST", "/_ml/datafeeds/cpu-feed/_start")
    assert status == 200 and started["processed"] == 125

    status, res = client.req(
        "GET", "/_ml/anomaly_detectors/cpu-job/results/buckets",
        {"anomaly_score": 50})
    assert res["count"] == 1
    assert res["buckets"][0]["timestamp"] == 20 * 60 * 1000
    client.req("POST", "/_ml/anomaly_detectors/cpu-job/_close")

    status, stats = client.req("GET", "/_ml/datafeeds/cpu-feed/_stats")
    assert stats["datafeeds"][0]["state"] == "stopped"


def test_record_for_finalized_bucket_dropped_not_misattributed():
    """After a flush finalizes bucket [0,60), a late record at t=50 must not
    land in the next bucket's results."""
    results = []
    proc = AutodetectProcess(
        {"job_id": "j", "analysis_config": {
            "bucket_span": 60, "detectors": [{"function": "count"}]},
         "data_description": {"time_field": "t"}}, results.append)
    proc.write_record(10, {"t": 10})
    proc.flush()                    # finalizes [0, 60)
    proc.write_record(50, {"t": 50})  # stale: bucket already closed
    proc.write_record(70, {"t": 70})
    proc.flush()
    proc.close()
    buckets = {m["timestamp"]: m["event_count"] for m in results
               if m["type"] == "bucket"}
    assert buckets == {0: 1, 60000: 1}  # t=50 dropped, not counted at 60000


def test_out_of_order_records_counted(node):
    node.ml.put_job("o1", {"analysis_config": {
        "bucket_span": 60, "detectors": [{"function": "count"}]},
        "data_description": {"time_field": "t"}})
    node.ml.open_job("o1")
    node.ml.post_data("o1", [{"t": 100}, {"t": 200}, {"t": 50}, {"t": 300}])
    counts = node.ml.data_counts["o1"]
    assert counts["processed_record_count"] == 3
    assert counts["out_of_order_timestamp_count"] == 1
    node.ml.close_job("o1")
