"""Blob store backends + snapshot repositories over them (reference:
common/blobstore, repository-url module, repository-s3 plugin tested
against the s3-fixture — SURVEY.md §2.10, §4.7)."""

import os

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.snapshots.blobstore import (
    BlobStoreError,
    FsBlobStore,
    MemoryBlobStore,
    S3BlobStore,
    UrlBlobStore,
    build_blob_store,
)
from tests.s3_fixture import S3Fixture


def _exercise(store):
    store.write_blob("blobs/abc", b"hello")
    store.write_blob("snapshots/s1.json", b"{}")
    assert store.read_blob("blobs/abc") == b"hello"
    assert store.exists("blobs/abc")
    assert not store.exists("blobs/zzz")
    assert store.list_blobs("snapshots/") == ["snapshots/s1.json"]
    store.delete_blob("blobs/abc")
    assert not store.exists("blobs/abc")
    with pytest.raises(BlobStoreError):
        store.read_blob("blobs/abc")


def test_fs_blob_store(tmp_path):
    _exercise(FsBlobStore(str(tmp_path / "repo")))


def test_fs_blob_store_rejects_traversal(tmp_path):
    store = FsBlobStore(str(tmp_path / "repo"))
    with pytest.raises(IllegalArgumentError):
        store.write_blob("../outside", b"x")
    # sibling dir sharing the root's name prefix must be rejected too
    with pytest.raises(IllegalArgumentError):
        store.write_blob("../repo-evil/x", b"x")


def test_url_repo_verify_fails_when_unreachable(tmp_path):
    from elasticsearch_tpu.snapshots.service import Repository
    from elasticsearch_tpu.snapshots.blobstore import (
        BlobStoreUnavailableError)
    repo = Repository("bad", "url",
                      {"url": "http://127.0.0.1:1/nope/"})
    with pytest.raises(BlobStoreUnavailableError):
        repo.verify()


def test_plugin_shadowed_builtin_restored_on_close(tmp_path):
    """A plugin overriding a built-in name must restore it on close, not
    destroy it process-wide."""
    import json as _json
    pdir = tmp_path / "plugins" / "shadow"
    pdir.mkdir(parents=True)
    (pdir / "plugin.py").write_text('''
from elasticsearch_tpu.plugins import Plugin
from elasticsearch_tpu.index.analysis import Analyzer, keyword_tokenizer

class Shadow(Plugin):
    name = "shadow"
    def get_analyzers(self):
        return [Analyzer("standard", keyword_tokenizer)]  # overrides builtin
''')
    from elasticsearch_tpu.index.analysis import DEFAULT_REGISTRY
    node = Node(str(tmp_path / "data"),
                settings={"path.plugins": str(tmp_path / "plugins")})
    assert DEFAULT_REGISTRY.get("standard").terms("A B") == ["A B"]  # shadowed
    node.close()
    assert DEFAULT_REGISTRY.get("standard").terms("A B") == ["a", "b"]  # back


def test_memory_blob_store_shared_by_name():
    a = MemoryBlobStore("shared-loc-test")
    b = MemoryBlobStore("shared-loc-test")
    a.write_blob("k", b"v")
    assert b.read_blob("k") == b"v"
    _exercise(MemoryBlobStore("other-loc-test"))


def test_s3_blob_store_against_fixture():
    with S3Fixture() as fx:
        store = S3BlobStore(fx.endpoint, "mybucket", base_path="backups")
        _exercise(store)
        # base_path prefixes keys on the wire
        store.write_blob("blobs/x", b"1")
        from tests.s3_fixture import _Handler
        assert ("mybucket", "backups/blobs/x") in _Handler.store


def test_url_blob_store_readonly(tmp_path):
    # file:// url over an fs repo written separately
    src = FsBlobStore(str(tmp_path / "served"))
    src.write_blob("snapshots/s1.json", b"{\"snapshot\": \"s1\"}")
    url = "file://" + str(tmp_path / "served") + "/"
    store = UrlBlobStore(url)
    assert store.read_blob("snapshots/s1.json") == b"{\"snapshot\": \"s1\"}"
    with pytest.raises(IllegalArgumentError):
        store.write_blob("x", b"y")
    with pytest.raises(IllegalArgumentError):
        store.delete_blob("x")


def test_build_blob_store_gating():
    with pytest.raises(IllegalArgumentError):
        build_blob_store("gcs", {})
    with pytest.raises(IllegalArgumentError):
        build_blob_store("s3", {"bucket": "b"})  # endpoint required
    with pytest.raises(IllegalArgumentError):
        build_blob_store("bogus", {})
    with pytest.raises(IllegalArgumentError):
        build_blob_store("fs", {})  # location required


# ------------------------------------------------------- end-to-end snapshot

def test_snapshot_restore_via_s3_repository(tmp_path):
    with S3Fixture() as fx:
        node = Node(str(tmp_path / "data"))
        try:
            node.index_doc("src", "1", {"v": "original"}, refresh="true")
            node.snapshots.put_repository("s3repo", {
                "type": "s3", "settings": {"endpoint": fx.endpoint,
                                           "bucket": "snaps",
                                           "base_path": "es"}})
            node.snapshots.create_snapshot("s3repo", "snap1",
                                           {"indices": "src"})
            assert node.snapshots.get_repository(
                "s3repo").list_snapshots() == ["snap1"]
            out = node.snapshots.restore_snapshot(
                "s3repo", "snap1", {"indices": "src",
                                    "rename_pattern": "src",
                                    "rename_replacement": "restored"})
            assert out["snapshot"]["indices"] == ["restored"]
            doc = node.get_doc("restored", "1")
            assert doc["_source"]["v"] == "original"
        finally:
            node.close()


def test_snapshot_restore_via_memory_repository(tmp_path):
    node = Node(str(tmp_path / "data"))
    try:
        node.index_doc("m", "1", {"v": 42}, refresh="true")
        node.snapshots.put_repository("mem", {
            "type": "memory", "settings": {"location": "snap-test-mem"}})
        node.snapshots.create_snapshot("mem", "s1", {"indices": "m"})
        node.snapshots.restore_snapshot("mem", "s1", {
            "indices": "m", "rename_pattern": "m",
            "rename_replacement": "m2"})
        assert node.get_doc("m2", "1")["_source"]["v"] == 42
    finally:
        node.close()


def test_restore_from_url_repository(tmp_path):
    """Write via fs, serve the same tree read-only via file:// url."""
    node = Node(str(tmp_path / "data"))
    try:
        node.index_doc("u", "1", {"v": "url"}, refresh="true")
        loc = str(tmp_path / "repo")
        node.snapshots.put_repository("w", {"type": "fs",
                                            "settings": {"location": loc}})
        node.snapshots.create_snapshot("w", "s1", {"indices": "u"})
        node.snapshots.put_repository("r", {
            "type": "url", "settings": {"url": "file://" + loc + "/"}})
        node.snapshots.restore_snapshot("r", "s1", {
            "indices": "u", "rename_pattern": "u",
            "rename_replacement": "u2"})
        assert node.get_doc("u2", "1")["_source"]["v"] == "url"
    finally:
        node.close()


def test_verify_repository_rest(tmp_path):
    import json
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    node = Node(str(tmp_path / "data"))
    try:
        rc = RestController()
        register_all(rc, node)
        status, _ = rc.dispatch(
            "PUT", "/_snapshot/vr", {},
            json.dumps({"type": "fs", "settings": {
                "location": str(tmp_path / "repo")}}).encode(),
            "application/json")
        assert status == 200
        status, body = rc.dispatch("POST", "/_snapshot/vr/_verify", {},
                                   b"", "application/json")
        assert status == 200 and node.node_id in body["nodes"]
    finally:
        node.close()


def test_s3_unavailable_is_not_missing():
    """Connection-level failures must surface as unavailability, never as
    a missing blob (ADVICE: restore during an outage must not claim data
    loss)."""
    from elasticsearch_tpu.snapshots.blobstore import (
        BlobStoreError, BlobStoreUnavailableError, S3BlobStore,
    )
    store = S3BlobStore(endpoint="http://127.0.0.1:1", bucket="b")
    with pytest.raises(BlobStoreUnavailableError):
        store.read_blob("any")
    with pytest.raises(BlobStoreUnavailableError):
        store.exists("any")
    with pytest.raises(BlobStoreUnavailableError):
        store.delete_blob("any")


def test_s3_sigv4_headers():
    """Credentialed requests carry a SigV4 Authorization header."""
    import http.server
    import threading

    from elasticsearch_tpu.snapshots.blobstore import S3BlobStore

    captured = {}

    class H(http.server.BaseHTTPRequestHandler):
        def do_PUT(self):
            captured.update(self.headers)
            length = int(self.headers.get("Content-Length", 0))
            self.rfile.read(length)
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        store = S3BlobStore(endpoint=f"http://127.0.0.1:{srv.server_port}",
                            bucket="b", access_key="AKIDEXAMPLE",
                            secret_key="secret", region="eu-west-1")
        store.write_blob("k/x", b"data")
    finally:
        srv.shutdown()
    auth = captured.get("Authorization", "")
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    assert "/eu-west-1/s3/aws4_request" in auth
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth
    lower = {k.lower() for k in captured}
    assert "x-amz-date" in lower and "x-amz-content-sha256" in lower


def test_repo_get_redacts_credentials(tmp_path):
    node = Node(str(tmp_path / "redact_node"))
    try:
        node.snapshots.put_repository(
            "sec", {"type": "memory",
                    "settings": {"location": "redact-me",
                                 "access_key": "AKID", "secret_key": "sss"}},
            verify=False)
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController
        rc = RestController()
        register_all(rc, node)
        status, body = rc.dispatch("GET", "/_snapshot/sec", {}, b"")
        assert status == 200
        s = body["sec"]["settings"]
        assert s["access_key"] == "<redacted>"
        assert s["secret_key"] == "<redacted>"
        assert s["location"] == "redact-me"
    finally:
        node.close()


def test_s3_creds_resolve_from_node_keystore_settings():
    from elasticsearch_tpu.snapshots.blobstore import build_blob_store
    store = build_blob_store(
        "s3", {"endpoint": "http://127.0.0.1:1", "bucket": "b",
               "client": "prod"},
        node_settings={"s3.client.prod.access_key": "FROMKS",
                       "s3.client.prod.secret_key": "KSSECRET"})
    assert store.access_key == "FROMKS" and store.secret_key == "KSSECRET"


# ---------------------------------------------------- gcs / azure dialects

def test_gcs_blob_store_against_fixture():
    from elasticsearch_tpu.snapshots.blobstore import GcsBlobStore
    from tests.cloud_fixtures import GcsFixture, _GcsHandler
    _GcsHandler.store.clear()
    with GcsFixture() as fx:
        store = GcsBlobStore(fx.endpoint, "mybucket", base_path="backups")
        _exercise(store)
        store.write_blob("blobs/x", b"1")
        assert ("mybucket", "backups/blobs/x") in _GcsHandler.store
        # listing follows nextPageToken across tiny fixture pages
        for i in range(5):
            store.write_blob(f"many/{i}", b"d")
        assert store.list_blobs("many/") == [f"many/{i}" for i in range(5)]


def test_azure_blob_store_against_fixture():
    import base64
    from elasticsearch_tpu.snapshots.blobstore import (
        AzureBlobStore, BlobStoreUnavailableError,
    )
    from tests.cloud_fixtures import AzureFixture, _AzureHandler
    _AzureHandler.store.clear()
    key = base64.b64encode(b"sekrit").decode()
    _AzureHandler.require_auth = ("acct", key)
    try:
        with AzureFixture() as fx:
            store = AzureBlobStore(fx.endpoint, "cont", base_path="es",
                                   account="acct", key=key)
            _exercise(store)
            # a WRONG key fails signature verification (Azurite-grade 403)
            bad = AzureBlobStore(fx.endpoint, "cont", account="acct",
                                 key=base64.b64encode(b"wrong").decode())
            with pytest.raises(BlobStoreUnavailableError):
                bad.write_blob("x", b"1")
    finally:
        _AzureHandler.require_auth = ()
    _AzureHandler.store.clear()
    with AzureFixture() as fx:
        store = AzureBlobStore(fx.endpoint, "cont", base_path="es",
                               account="acct", key=key)
        store.write_blob("blobs/x", b"1")
        assert ("cont", "es/blobs/x") in _AzureHandler.store
        for i in range(5):
            store.write_blob(f"many/{i}", b"d")
        assert store.list_blobs("many/") == [f"many/{i}" for i in range(5)]


def test_snapshot_restore_via_gcs_and_azure(tmp_path):
    from tests.cloud_fixtures import (
        AzureFixture, GcsFixture, _AzureHandler, _GcsHandler,
    )
    _GcsHandler.store.clear()
    _AzureHandler.store.clear()
    with GcsFixture() as gfx, AzureFixture() as afx:
        node = Node(str(tmp_path / "data"))
        try:
            node.index_doc("src", "1", {"v": "original"}, refresh="true")
            for rname, rtype, settings in (
                    ("gcsrepo", "gcs", {"endpoint": gfx.endpoint,
                                        "bucket": "snaps",
                                        "base_path": "es"}),
                    ("azrepo", "azure", {"endpoint": afx.endpoint,
                                         "container": "snaps",
                                         "base_path": "es"})):
                node.snapshots.put_repository(
                    rname, {"type": rtype, "settings": settings})
                node.snapshots.create_snapshot(rname, "snap1",
                                               {"indices": "src"})
                assert node.snapshots.get_repository(
                    rname).list_snapshots() == ["snap1"]
                out = node.snapshots.restore_snapshot(
                    rname, "snap1",
                    {"indices": "src", "rename_pattern": "src",
                     "rename_replacement": f"restored_{rtype}"})
                assert out["snapshot"]["indices"] == [f"restored_{rtype}"]
                doc = node.get_doc(f"restored_{rtype}", "1")
                assert doc["_source"]["v"] == "original"
        finally:
            node.close()


def test_hdfs_still_gated():
    with pytest.raises(IllegalArgumentError):
        build_blob_store("hdfs", {})
