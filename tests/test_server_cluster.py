"""Multi-process cluster boot: three OS processes started from the CLI form
a cluster, elect a master, replicate an index, and serve _search and
_cluster/health from any node's HTTP port (reference: `node/Node.java:502,682`
production wiring of TransportService + Coordinator + REST)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _req(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.fixture(scope="module")
def cluster_procs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("proc_cluster")
    http_ports = _free_ports(3)
    tp_ports = _free_ports(3)
    seeds = ",".join(f"127.0.0.1:{p}" for p in tp_ports)
    masters = "n0,n1,n2"
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for i in range(3):
        cmd = [sys.executable, "-m", "elasticsearch_tpu.server",
               "--port", str(http_ports[i]), "--name", f"n{i}",
               "--data", str(tmp / f"n{i}"),
               "-E", f"transport.port={tp_ports[i]}",
               "-E", f"discovery.seed_hosts={seeds}",
               "-E", f"cluster.initial_master_nodes={masters}"]
        procs.append(subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(tmp / f"n{i}.log", "w"), stderr=subprocess.STDOUT))
    yield http_ports, tp_ports, procs, tmp
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def _wait_health(port, want="green", deadline_s=90, nodes=None):
    """Poll across slow interpreter startup (jax import dominates)."""
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        try:
            h = _req("GET", f"http://127.0.0.1:{port}/_cluster/health"
                            f"?wait_for_status={want}&timeout=5s", timeout=15)
            last = h
            ok = h["status"] == want or (
                want == "yellow" and h["status"] == "green")
            if ok and (nodes is None or h["number_of_nodes"] >= nodes):
                return h
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(1.0)
    raise AssertionError(f"cluster never reached {want}: {last}")


def test_three_process_cluster_forms_and_replicates(cluster_procs):
    http_ports, _tp, procs, tmp = cluster_procs
    h = _wait_health(http_ports[0], "green", nodes=3)
    assert h["number_of_nodes"] == 3, h
    assert h["master_node"] in ("n0", "n1", "n2")

    # create a replicated index through node 1
    r = _req("PUT", f"http://127.0.0.1:{http_ports[1]}/events", {
        "settings": {"index.number_of_shards": 2,
                     "index.number_of_replicas": 1},
        "mappings": {"properties": {"msg": {"type": "text"},
                                    "n": {"type": "long"}}}})
    assert r["acknowledged"]
    deadline = time.monotonic() + 60
    h = None
    while time.monotonic() < deadline:
        h = _req("GET", f"http://127.0.0.1:{http_ports[1]}/_cluster/health")
        if h["status"] == "green" and h["active_shards"] == 4:
            break
        time.sleep(0.5)
    assert h["active_shards"] == 4, h  # 2 primaries + 2 replicas

    # write through node 2 (reroutes to primaries wherever they live)
    for i in range(12):
        r = _req("PUT",
                 f"http://127.0.0.1:{http_ports[2]}/events/_doc/{i}",
                 {"msg": f"event number {i}", "n": i})
        assert r["result"] == "created", r

    _req("POST", f"http://127.0.0.1:{http_ports[0]}/events/_refresh")

    # search via every node: same distributed result
    for port in http_ports:
        resp = _req("POST", f"http://127.0.0.1:{port}/events/_search",
                    {"query": {"match": {"msg": "event"}}, "size": 20,
                     "sort": [{"n": "asc"}]})
        assert resp["hits"]["total"]["value"] == 12, (port, resp["hits"])
        assert [hit["_source"]["n"] for hit in resp["hits"]["hits"]] == list(range(12))
        assert resp["_shards"]["failed"] == 0

    # distributed aggs over HTTP from a non-master node
    resp = _req("POST", f"http://127.0.0.1:{http_ports[2]}/events/_search",
                {"size": 0, "aggs": {"m": {"avg": {"field": "n"}}}})
    assert abs(resp["aggregations"]["m"]["value"] - 5.5) < 1e-9

    # realtime get via any node
    got = _req("GET", f"http://127.0.0.1:{http_ports[0]}/events/_doc/7")
    assert got["found"] and got["_source"]["n"] == 7

    # _cat/nodes shows all three with the master marked
    req = urllib.request.Request(
        f"http://127.0.0.1:{http_ports[0]}/_cat/nodes",
        headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        text = resp.read().decode()
    assert text.count("\n") >= 3 and "*" in text


def test_master_failover_across_processes(cluster_procs):
    http_ports, _tp, procs, tmp = cluster_procs
    h = _wait_health(http_ports[0], "green")
    master = h["master_node"]
    master_idx = int(master[1])
    # kill the master process outright
    procs[master_idx].kill()
    procs[master_idx].wait(timeout=10)
    survivors = [p for i, p in enumerate(http_ports) if i != master_idx]
    deadline = time.monotonic() + 60
    new_master = None
    while time.monotonic() < deadline:
        try:
            h = _req("GET", f"http://127.0.0.1:{survivors[0]}/_cluster/health",
                     timeout=5)
            if h["master_node"] and h["master_node"] != master \
                    and h["number_of_nodes"] == 2:
                new_master = h["master_node"]
                break
        except Exception:
            pass
        time.sleep(1.0)
    assert new_master, "no re-election after master death"
    # the surviving cluster still serves reads and writes
    r = _req("PUT", f"http://127.0.0.1:{survivors[1]}/events/_doc/100",
             {"msg": "after failover", "n": 100})
    assert r["result"] == "created"
    _req("POST", f"http://127.0.0.1:{survivors[0]}/events/_refresh")
    resp = _req("POST", f"http://127.0.0.1:{survivors[0]}/events/_search",
                {"query": {"term": {"n": 100}}})
    assert resp["hits"]["total"]["value"] == 1


def test_parse_time_units():
    from elasticsearch_tpu.rest.cluster_actions import _parse_time_s
    assert _parse_time_s("30s") == 30.0
    assert _parse_time_s("1m") == 60.0
    assert _parse_time_s("500ms") == 0.5
    assert _parse_time_s("2") == 2.0


def test_create_semantics_and_refresh_shape(cluster_procs):
    http_ports, _tp, procs, tmp = cluster_procs
    # runs after the failover test: one process may be dead — pick a live one
    port = None
    for i, p in enumerate(procs):
        if p.poll() is None:
            port = http_ports[i]
            break
    assert port is not None
    _wait_health(port, "yellow", nodes=2)
    try:
        _req("PUT", f"http://127.0.0.1:{port}/events2",
             {"settings": {"index.number_of_shards": 1,
                           "index.number_of_replicas": 0}})
    except urllib.error.HTTPError:
        pass
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        h = _req("GET", f"http://127.0.0.1:{port}/_cluster/health")
        if h["status"] in ("green", "yellow") and h["active_primary_shards"] >= 1:
            break
        time.sleep(0.5)
    r = _req("PUT", f"http://127.0.0.1:{port}/events2/_create/c1", {"v": 1})
    assert r["result"] == "created"
    # second _create of the same id must NOT silently overwrite
    try:
        r2 = _req("PUT", f"http://127.0.0.1:{port}/events2/_create/c1", {"v": 2})
        raise AssertionError(f"_create overwrote existing doc: {r2}")
    except urllib.error.HTTPError as e:
        assert e.code in (409, 400, 500), e.code
    # refresh response reports real per-node counts
    rr = _req("POST", f"http://127.0.0.1:{port}/events2/_refresh")
    assert rr["_shards"]["successful"] >= 1
    assert rr["_shards"]["failed"] == 0
    got = _req("GET", f"http://127.0.0.1:{port}/events2/_doc/c1")
    assert got["_source"]["v"] == 1  # first write won


def test_tls_cluster_forms_and_rejects_plaintext(tmp_path):
    """Two CLI-booted processes form a cluster over mutual-TLS transport
    with signed auth contexts; a plaintext socket poking the transport port
    gets no cluster access (transport/tls.py)."""
    pytest.importorskip("cryptography")
    from elasticsearch_tpu.transport.tls import generate_ca, generate_node_cert

    certs_dir = str(tmp_path / "certs")
    ca = generate_ca(certs_dir)
    node_cert = generate_node_cert(certs_dir, ca["cert"], ca["key"],
                                   name="node", hosts=["127.0.0.1"])

    http_ports = _free_ports(2)
    tp_ports = _free_ports(2)
    seeds = ",".join(f"127.0.0.1:{p}" for p in tp_ports)
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        for i in range(2):
            cmd = [sys.executable, "-m", "elasticsearch_tpu.server",
                   "--port", str(http_ports[i]), "--name", f"t{i}",
                   "--data", str(tmp_path / f"t{i}"),
                   "-E", f"transport.port={tp_ports[i]}",
                   "-E", f"discovery.seed_hosts={seeds}",
                   "-E", "cluster.initial_master_nodes=t0,t1",
                   "-E", "transport.ssl.enabled=true",
                   "-E", f"transport.ssl.certificate={node_cert['cert']}",
                   "-E", f"transport.ssl.key={node_cert['key']}",
                   "-E", f"transport.ssl.certificate_authorities={ca['cert']}",
                   "-E", "transport.ssl.verification_mode=certificate",
                   "-E", "cluster.auth.key=test-cluster-secret"]
            procs.append(subprocess.Popen(
                cmd, cwd=REPO, env=env,
                stdout=open(tmp_path / f"t{i}.log", "w"),
                stderr=subprocess.STDOUT))

        h = _wait_health(http_ports[0], "green", nodes=2)
        assert h["number_of_nodes"] == 2, h

        # index + search across the TLS transport
        r = _req("PUT", f"http://127.0.0.1:{http_ports[0]}/sec",
                 {"settings": {"index.number_of_shards": 1,
                               "index.number_of_replicas": 1},
                  "mappings": {"properties": {"n": {"type": "long"}}}})
        assert r["acknowledged"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            h = _req("GET", f"http://127.0.0.1:{http_ports[0]}/_cluster/health")
            if h["status"] == "green" and h["active_shards"] == 2:
                break
            time.sleep(0.5)
        assert h["active_shards"] == 2, h
        _req("PUT", f"http://127.0.0.1:{http_ports[1]}/sec/_doc/1", {"n": 1})
        _req("POST", f"http://127.0.0.1:{http_ports[0]}/sec/_refresh")
        resp = _req("POST", f"http://127.0.0.1:{http_ports[1]}/sec/_search",
                    {"query": {"match_all": {}}})
        assert resp["hits"]["total"]["value"] == 1

        # a plaintext TCP client cannot speak to the TLS transport port
        s = socket.create_connection(("127.0.0.1", tp_ports[0]), timeout=5)
        try:
            s.sendall(b"ET\x00\x00\x00\x0bplaintext!!")
            s.settimeout(5)
            data = s.recv(1024)
            # TLS server either drops the connection or answers with a TLS
            # alert (0x15) — never a framed 'ET' protocol response
            assert not data.startswith(b"ET")
        except (ConnectionError, socket.timeout, OSError):
            pass
        finally:
            s.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_unified_feature_surface_in_cluster_mode(cluster_procs):
    """The full single-node REST surface works against a clustered
    deployment (ClusterAwareNode): update-by-script, mget, msearch, count,
    analyze, ingest pipelines — with the data path distributed."""
    http_ports, _tp, procs, tmp = cluster_procs
    # the failover test may have killed a process: use live nodes only
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    assert len(live) >= 2, "not enough live nodes"
    _wait_health(live[0], "green", nodes=len(live))
    base = f"http://127.0.0.1:{live[0]}"

    r = _req("PUT", f"{base}/uni", {
        "settings": {"index.number_of_shards": 2,
                     "index.number_of_replicas": 1},
        "mappings": {"properties": {"n": {"type": "long"}}}})
    assert r["acknowledged"]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        h = _req("GET", f"{base}/_cluster/health")
        if h["status"] == "green" and h.get("active_shards", 0) >= 4:
            break
        time.sleep(0.5)
    for i in range(8):
        _req("PUT", f"{base}/uni/_doc/{i}?refresh=true", {"n": i})

    # update by painless script, read back through another node
    r = _req("POST", f"{base}/uni/_update/3",
             {"script": {"source": "ctx._source.n += 100"}})
    assert r["result"] == "updated"
    got = _req("GET", f"http://127.0.0.1:{live[-1]}/uni/_doc/3")
    assert got["_source"]["n"] == 103

    # mget across shards
    r = _req("POST", f"{base}/uni/_mget", {"ids": ["1", "5", "7"]})
    assert [d["_source"]["n"] for d in r["docs"]] == [1, 5, 7]

    # count + msearch + aggs through the distributed search path
    _req("POST", f"{base}/uni/_refresh")
    r = _req("POST", f"{base}/uni/_count",
             {"query": {"range": {"n": {"lt": 5}}}})
    assert r["count"] == 4  # 0,1,2,4 (3 became 103)
    nd = b"".join(json.dumps(line).encode() + b"\n" for line in
                  [{"index": "uni"}, {"query": {"match_all": {}}, "size": 0},
                   {"index": "uni"},
                   {"size": 0, "aggs": {"s": {"sum": {"field": "n"}}}}])
    mreq = urllib.request.Request(
        f"{base}/_msearch", data=nd, method="POST",
        headers={"Content-Type": "application/x-ndjson"})
    with urllib.request.urlopen(mreq, timeout=10) as resp:
        r = json.loads(resp.read())
    assert r["responses"][0]["hits"]["total"]["value"] == 8
    assert r["responses"][1]["aggregations"]["s"]["value"] == \
        sum(range(8)) - 3 + 103

    # analyze (node-local service, same surface)
    r = _req("POST", f"{base}/_analyze",
             {"analyzer": "standard", "text": "Quick Brown Foxes"})
    assert [t["token"] for t in r["tokens"]] == ["quick", "brown", "foxes"]

    # ingest pipeline applied on write
    _req("PUT", f"{base}/_ingest/pipeline/addtag",
         {"processors": [{"set": {"field": "tag", "value": "p"}}]})
    _req("PUT", f"{base}/uni/_doc/99?pipeline=addtag&refresh=true", {"n": 99})
    got = _req("GET", f"{base}/uni/_doc/99")
    assert got["_source"]["tag"] == "p"

    # wildcard search spans the cluster metadata
    r = _req("POST", f"{base}/un*/_search",
             {"size": 0, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 9


def test_cluster_scroll_and_bulk_refresh(cluster_procs):
    """Scroll works on clustered deployments (coordinator page snapshot)
    and bulk?refresh=true refreshes through the cluster, not the empty
    node-local indices service."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    base = f"http://127.0.0.1:{live[0]}"
    _wait_health(live[0], "green", nodes=len(live))

    # bulk with refresh=true: previously 404'd on the local refresh epilogue
    nd = b""
    for i in range(15):
        nd += json.dumps({"index": {"_index": "scr", "_id": str(i)}}).encode() + b"\n"
        nd += json.dumps({"n": i}).encode() + b"\n"
    breq = urllib.request.Request(
        f"{base}/_bulk?refresh=true", data=nd, method="POST",
        headers={"Content-Type": "application/x-ndjson"})
    with urllib.request.urlopen(breq, timeout=20) as resp:
        r = json.loads(resp.read())
    assert not r["errors"], r

    # scroll through the distributed result in pages of 6
    r = _req("POST", f"{base}/scr/_search?scroll=1m",
             {"query": {"match_all": {}}, "size": 6,
              "sort": [{"n": "asc"}]})
    sid = r["_scroll_id"]
    got = [h["_source"]["n"] for h in r["hits"]["hits"]]
    assert r["hits"]["total"]["value"] == 15
    while True:
        r = _req("POST", f"{base}/_search/scroll",
                 {"scroll": "1m", "scroll_id": sid})
        if not r["hits"]["hits"]:
            break
        got.extend(h["_source"]["n"] for h in r["hits"]["hits"])
    assert got == list(range(15))


def test_cluster_scroll_beyond_10k_docs(cluster_procs):
    """Deep distributed pagination: per-shard pinned scroll contexts mean
    a scroll over >10k docs returns EVERY doc exactly once (the round-2
    coordinator snapshot silently truncated at 10k). Also: clearing the
    scroll frees the shard contexts, and an expired/cleared id 404s."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    base = f"http://127.0.0.1:{live[0]}"
    _wait_health(live[0], "green", nodes=len(live))

    _req("PUT", f"{base}/deep",
         {"settings": {"number_of_shards": 3, "number_of_replicas": 0}})
    n_docs = 12_000
    for lo in range(0, n_docs, 2000):
        nd = b""
        for i in range(lo, lo + 2000):
            nd += json.dumps(
                {"index": {"_index": "deep", "_id": str(i)}}).encode() + b"\n"
            nd += json.dumps({"n": i}).encode() + b"\n"
        breq = urllib.request.Request(
            f"{base}/_bulk", data=nd, method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        with urllib.request.urlopen(breq, timeout=60) as resp:
            r = json.loads(resp.read())
        assert not r["errors"]
    _req("POST", f"{base}/deep/_refresh", {})

    r = _req("POST", f"{base}/deep/_search?scroll=1m",
             {"query": {"match_all": {}}, "size": 500,
              "sort": [{"n": "asc"}]})
    assert r["hits"]["total"]["value"] == n_docs
    sid = r["_scroll_id"]
    got = [h["_source"]["n"] for h in r["hits"]["hits"]]
    while True:
        r = _req("POST", f"{base}/_search/scroll",
                 {"scroll": "1m", "scroll_id": sid})
        if not r["hits"]["hits"]:
            break
        got.extend(h["_source"]["n"] for h in r["hits"]["hits"])
    assert len(got) == n_docs, f"scroll returned {len(got)} of {n_docs}"
    assert got == list(range(n_docs))

    # clear frees the per-shard contexts; a further page 404s
    dreq = urllib.request.Request(
        f"{base}/_search/scroll", method="DELETE",
        data=json.dumps({"scroll_id": sid}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(dreq, timeout=20) as resp:
        r = json.loads(resp.read())
    assert r["succeeded"]
    try:
        _req("POST", f"{base}/_search/scroll",
             {"scroll": "1m", "scroll_id": sid})
        raise AssertionError("expected 404 after clear_scroll")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_registries_replicate_through_cluster_state(cluster_procs):
    """A pipeline/template/stored-script PUT on one node is usable on EVERY
    node (IngestMetadata/IndexTemplateMetaData/ScriptMetaData analogs)."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    assert len(live) >= 2
    a, b = f"http://127.0.0.1:{live[0]}", f"http://127.0.0.1:{live[-1]}"
    _wait_health(live[0], "green", nodes=len(live))

    # pipeline PUT on node a, used via ?pipeline= on node b
    _req("PUT", f"{a}/_ingest/pipeline/repl",
         {"processors": [{"set": {"field": "via", "value": "repl"}}]})
    deadline = time.monotonic() + 30
    applied = False
    while time.monotonic() < deadline:
        try:
            r = _req("GET", f"{b}/_ingest/pipeline/repl")
            if "repl" in r:
                applied = True
                break
        except urllib.error.HTTPError:
            time.sleep(0.3)
    assert applied, "pipeline did not replicate"
    _req("PUT", f"{b}/rrr/_doc/1?pipeline=repl&refresh=true", {"n": 1})
    got = _req("GET", f"{b}/rrr/_doc/1")
    assert got["_source"]["via"] == "repl"

    # stored script PUT on b, executed in a search on a
    _req("PUT", f"{b}/_scripts/replscore",
         {"script": {"lang": "painless", "source": "doc['n'].value * 10"}})
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            _req("GET", f"{a}/_scripts/replscore")
            break
        except urllib.error.HTTPError:
            time.sleep(0.3)
    r = _req("POST", f"{a}/rrr/_search",
             {"query": {"script_score": {"query": {"match_all": {}},
                                         "script": {"id": "replscore"}}}})
    assert r["hits"]["hits"][0]["_score"] == 10.0

    # template PUT on a governs auto-created index written through b
    _req("PUT", f"{a}/_template/repltpl",
         {"index_patterns": ["tpl-*"],
          "mappings": {"properties": {"z": {"type": "keyword"}}}})
    time.sleep(1.0)
    _req("PUT", f"{b}/tpl-one/_doc/1?refresh=true", {"z": "x"})
    m = _req("GET", f"{b}/tpl-one/_mapping")
    assert m["tpl-one"]["mappings"]["properties"]["z"]["type"] == "keyword"


def test_watcher_runs_as_persistent_task(cluster_procs):
    """Watches replicate through cluster state and execute on exactly ONE
    cluster-assigned node (PersistentTasksClusterService); execution
    survives the owning node's death (VERDICT r2 item 5)."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    a, b = f"http://127.0.0.1:{live[0]}", f"http://127.0.0.1:{live[-1]}"
    _wait_health(live[0], "green", nodes=len(live))

    # PUT on node a; the registry replicates it to every node
    _req("PUT", f"{a}/_watcher/watch/fire", {
        "trigger": {"schedule": {"interval": "1s"}},
        "actions": {"log": {"index": {"index": "firelog"}}}})
    deadline = time.monotonic() + 30
    r = None
    while time.monotonic() < deadline:
        try:
            r = _req("GET", f"{b}/_watcher/watch/fire")
            break
        except urllib.error.HTTPError:
            time.sleep(0.5)
    assert r and r["found"], "watch did not replicate"

    def count_fires(base):
        try:
            _req("POST", f"{base}/firelog/_refresh", {})
            return _req("GET", f"{base}/firelog/_count")["count"]
        except urllib.error.HTTPError:
            return 0

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and count_fires(a) < 2:
        time.sleep(1.0)
    c1 = count_fires(a)
    assert c1 >= 2, "watch never fired through the persistent task"

    # exactly-once: over the next ~4 ticks the count grows about one per
    # tick — three nodes each ticking would grow it ~3x per second
    time.sleep(4.0)
    c2 = count_fires(a)
    grown = c2 - c1
    assert 1 <= grown <= 8, f"fired {grown} times in 4s (multi-owner?)"

    # find the assigned node and kill it; a survivor takes over
    # (needs quorum AFTER the kill: earlier tests may have downed a node)
    still_live = [i for i, p in enumerate(procs) if p.poll() is None]
    if len(still_live) < 3:
        return
    state = _req("GET", f"{a}/_cluster/state")
    tasks = state["metadata"].get("__persistent_tasks__") or {}
    owner = tasks.get("watcher", {}).get("assigned_node")
    assert owner, f"no watcher assignment in {list(tasks)}"
    idx = int(owner[1:])  # names are n0/n1/n2
    procs[idx].send_signal(signal.SIGKILL)
    survivor_port = next(p for i, p in enumerate(http_ports) if i != idx)
    base_s = f"http://127.0.0.1:{survivor_port}"
    _wait_health(survivor_port, "yellow", nodes=2, deadline_s=120)
    c3 = count_fires(base_s)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and count_fires(base_s) <= c3 + 1:
        time.sleep(1.0)
    assert count_fires(base_s) > c3 + 1, "watch did not survive owner death"


def test_nodes_fanout_actions(cluster_procs):
    """The generic routed-action layer (cluster/cluster_node.py
    NODES_DISPATCH + fanout_nodes): `_nodes/stats`, `_nodes`, `_tasks` and
    hot-threads asked of ANY node reflect EVERY node — round 3 answered
    these with node-local state."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    assert len(live) >= 2, "not enough live nodes"
    _wait_health(live[0], "green", nodes=len(live))

    for port in (live[0], live[-1]):  # same answer regardless of target
        base = f"http://127.0.0.1:{port}"
        stats = _req("GET", f"{base}/_nodes/stats")
        assert stats["_nodes"]["successful"] == len(live)
        assert len(stats["nodes"]) == len(live)
        names = {n["name"] for n in stats["nodes"].values()}
        assert len(names) == len(live)  # distinct per-node sections
        for section in stats["nodes"].values():
            assert "jvm" in section and "thread_pool" in section

        info = _req("GET", f"{base}/_nodes")
        assert info["_nodes"]["successful"] == len(live)
        assert all("version" in n for n in info["nodes"].values())

        tasks = _req("GET", f"{base}/_tasks")
        assert len(tasks["nodes"]) == len(live)

    # hot threads: one ::: {node} section per node
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{live[0]}/_nodes/hot_threads",
            timeout=30) as resp:
        text = resp.read().decode()
    assert text.count(":::") == len(live)


def test_cluster_state_driven_snapshots(cluster_procs, tmp_path):
    """Snapshot lifecycle through cluster state (cluster/snapshots.py):
    the master assigns per-shard upload tasks to the nodes HOLDING the
    shards, so a snapshot captures ALL shards — round 3's node-local path
    silently captured only the receiving node's. Restore re-enters
    allocation with the repository as recovery source."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    assert len(live) >= 2, "not enough live nodes"
    _wait_health(live[0], "green", nodes=len(live))
    base = f"http://127.0.0.1:{live[0]}"
    other = f"http://127.0.0.1:{live[-1]}"

    repo_loc = str(tmp / "shared_repo")  # shared fs: all procs on this host
    r = _req("PUT", f"{base}/_snapshot/csrepo",
             {"type": "fs", "settings": {"location": repo_loc}})
    assert r["acknowledged"]

    r = _req("PUT", f"{base}/snapidx", {
        "settings": {"index.number_of_shards": 2,
                     "index.number_of_replicas": 0}})
    assert r["acknowledged"]
    for i in range(20):
        _req("PUT", f"{base}/snapidx/_doc/{i}?refresh=true", {"n": i})

    # the repo definition replicated: the OTHER node can snapshot
    r = _req("PUT", f"{other}/_snapshot/csrepo/snap1",
             {"indices": "snapidx"}, timeout=90)
    snap = r["snapshot"]
    assert snap["state"] == "SUCCESS", snap
    assert snap["shards"]["total"] == 2, snap     # BOTH primaries captured
    assert snap["shards"]["successful"] == 2, snap

    got = _req("GET", f"{base}/_snapshot/csrepo/snap1")
    assert got["snapshots"][0]["state"] == "SUCCESS"
    assert got["snapshots"][0]["indices"] == ["snapidx"]

    # wipe the index cluster-wide, restore from the snapshot on any node
    _req("DELETE", f"{base}/snapidx")
    r = _req("POST", f"{other}/_snapshot/csrepo/snap1/_restore",
             {"indices": "snapidx"}, timeout=90)
    assert r["snapshot"]["indices"] == ["snapidx"]

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            _req("POST", f"{base}/snapidx/_refresh")
            c = _req("POST", f"{base}/snapidx/_count",
                     {"query": {"match_all": {}}})
            if c["count"] == 20:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert c["count"] == 20
    # every doc is readable through the cluster read path
    got = _req("GET", f"{other}/snapidx/_doc/7")
    assert got["_source"]["n"] == 7


def test_rollup_job_as_persistent_task(cluster_procs):
    """Rollup jobs replicate through cluster state and tick on ONE
    cluster-assigned node (RollupJobTask as a persistent task): the rolled
    index materializes, survives the owner's death, and rollup-search
    keeps answering (VERDICT r3 item 9)."""
    http_ports, _tp, procs, tmp = cluster_procs
    live = [http_ports[i] for i, p in enumerate(procs) if p.poll() is None]
    assert len(live) >= 2
    a, b = f"http://127.0.0.1:{live[0]}", f"http://127.0.0.1:{live[-1]}"
    _wait_health(live[0], "green", nodes=len(live))

    _req("PUT", f"{a}/sensor", {"mappings": {"properties": {
        "ts": {"type": "date"}, "node": {"type": "keyword"},
        "temp": {"type": "double"}}}})
    for i, (n, t) in enumerate([("n1", 10.0), ("n1", 20.0), ("n2", 30.0)]):
        _req("PUT", f"{a}/sensor/_doc/{i}?refresh=true",
             {"ts": f"2020-01-01T0{i}:00:00Z", "node": n, "temp": t})

    _req("PUT", f"{a}/_rollup/job/sj", {
        "index_pattern": "sensor", "rollup_index": "sensor_rollup",
        "cron": "* * * * *", "page_size": 100,
        "groups": {"date_histogram": {"field": "ts",
                                      "calendar_interval": "1h"},
                   "terms": {"fields": ["node"]}},
        "metrics": [{"field": "temp", "metrics": ["max", "min", "avg"]}]})
    # config replicated: the job is visible from the OTHER node
    deadline = time.monotonic() + 30
    seen = False
    while time.monotonic() < deadline:
        try:
            r = _req("GET", f"{b}/_rollup/job/sj")
            seen = bool(r["jobs"])
            if seen:
                break
        except urllib.error.HTTPError:
            pass
        time.sleep(0.5)
    assert seen, "rollup job did not replicate"

    _req("POST", f"{b}/_rollup/job/sj/_start", {})

    def rolled_buckets(base):
        """The observable rollup fingerprint: the distinct
        (hour-bucket, node) keys materialized in the rolled index.
        Bucket doc-ids are deterministic (re-rolls are idempotent
        upserts), so this SET is what a completed pass guarantees —
        unlike a raw doc count, it can't race a tick that is mid-pass,
        and waiting for a specific new key can't be satisfied by stale
        buckets (the wall-clock tick-count flake of VERDICT r3/r5)."""
        try:
            _req("POST", f"{base}/sensor_rollup/_refresh", {})
            r = _req("POST", f"{base}/sensor_rollup/_search",
                     {"size": 100, "query": {"match_all": {}}})
            return {(h["_source"].get("ts.date_histogram"),
                     h["_source"].get("node.terms"))
                    for h in r["hits"]["hits"]}
        except urllib.error.HTTPError:
            return set()

    def wait_rolled(base, predicate, timeout=150):
        # generous: the full suite runs this under heavy CPU contention
        # from sibling JAX subprocesses, and the persistent-task tick
        # interval stretches with load
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = rolled_buckets(base)
            if predicate(got):
                return got
            time.sleep(1.0)
        return rolled_buckets(base)

    got = wait_rolled(a, lambda s: len(s) >= 3)
    assert len(got) == 3, f"rollup docs did not materialize: {got}"
    assert {n for _, n in got} == {"n1", "n2"}

    # new source data keeps flowing into the rolled index via the ticking
    # persistent task: wait for the NEW bucket key, not a count
    _req("PUT", f"{a}/sensor/_doc/9?refresh=true",
         {"ts": "2020-01-01T09:00:00Z", "node": "n3", "temp": 40.0})
    got = wait_rolled(a, lambda s: any(n == "n3" for _, n in s))
    assert any(n == "n3" for _, n in got), \
        f"rollup task is not ticking: {got}"

    # kill the assigned owner; a survivor takes over the task
    still_live = [i for i, p in enumerate(procs) if p.poll() is None]
    if len(still_live) < 3:
        return  # not enough quorum to survive another kill
    state = _req("GET", f"{a}/_cluster/state")
    tasks = state["metadata"].get("__persistent_tasks__") or {}
    owner = tasks.get("rollup", {}).get("assigned_node")
    assert owner, f"no rollup assignment in {list(tasks)}"
    idx = int(owner[1:])
    procs[idx].send_signal(signal.SIGKILL)
    survivors = [p for i, p in enumerate(http_ports)
                 if i != idx and procs[i].poll() is None]
    base_s = f"http://127.0.0.1:{survivors[0]}"
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            if _req("GET", f"{base_s}/_cluster/health")["number_of_nodes"] \
                    == len(survivors):
                break
        except Exception:
            pass
        time.sleep(1.0)
    _req("PUT", f"{base_s}/sensor/_doc/10?refresh=true",
         {"ts": "2020-01-01T10:00:00Z", "node": "n4", "temp": 50.0})
    got = wait_rolled(base_s, lambda s: any(n == "n4" for _, n in s),
                      timeout=90)
    assert any(n == "n4" for _, n in got), \
        f"rollup task did not fail over: {got}"
