"""Bootstrap hardening + checks, systemd notify, plugin CLI (reference:
bootstrap/Bootstrap.java natives + BootstrapChecks.java, JNANatives /
SystemCallFilter, modules/systemd, distribution/tools/plugin-cli)."""

import os
import socket
import struct
import subprocess
import sys

import pytest

from elasticsearch_tpu import bootstrap


def test_bpf_program_shape():
    prog = bootstrap._build_bpf_program()
    assert len(prog) % 8 == 0
    n = len(prog) // 8
    # arch load + arch jump + nr load + one jump per blocked + 2 returns
    assert n == 3 + len(bootstrap._X86_64_BLOCKED) + 2
    # last two instructions: RET ALLOW then RET ERRNO|EACCES
    code, jt, jf, k = struct.unpack("<HBBI", prog[-16:-8])
    assert code == bootstrap._BPF_RET_K and k == bootstrap._SECCOMP_RET_ALLOW
    code, jt, jf, k = struct.unpack("<HBBI", prog[-8:])
    assert k == (bootstrap._SECCOMP_RET_ERRNO | bootstrap._EACCES)
    # arch-mismatch bailout must land on RET ALLOW (idx n-2), not RET ERRNO:
    # from idx 1, target = 1 + 1 + jf  →  jf = n - 4
    code, jt, jf, k = struct.unpack("<HBBI", prog[8:16])
    assert k == bootstrap._AUDIT_ARCH_X86_64
    assert 1 + 1 + jf == n - 2, "non-x86_64 ABIs must be allowed through"


def test_seccomp_filter_blocks_exec_in_subprocess():
    """Install the filter in a throwaway subprocess and verify exec is
    denied with EACCES while normal syscalls keep working."""
    code = r"""
import os, sys
sys.path.insert(0, ".")
from elasticsearch_tpu.bootstrap import Natives
n = Natives()
n.try_seccomp_filter()
if not n.seccomp_installed:
    print("SKIP:" + ";".join(n.errors)); sys.exit(0)
open("/dev/null").close()  # ordinary syscalls still allowed
try:
    os.execv("/bin/true", ["/bin/true"])
    print("EXEC-SUCCEEDED")
except PermissionError:
    print("EXEC-BLOCKED")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=".",
                       env={"PYTHONPATH": ".", "PATH": "/usr/bin:/bin"})
    out = r.stdout.strip()
    if out.startswith("SKIP:"):
        pytest.skip(out)
    assert out == "EXEC-BLOCKED", (r.stdout, r.stderr)


def test_mlockall_attempt_reports():
    n = bootstrap.Natives()
    n.try_mlockall()
    # either it locked, or it reported a clear rlimit error
    assert n.memory_locked or any("mlockall" in e for e in n.errors)


def test_bootstrap_checks_warn_and_enforce(tmp_path):
    warnings = bootstrap.run_bootstrap_checks(
        {"bootstrap.memory_lock": "false", "path.data": str(tmp_path / "d")})
    assert isinstance(warnings, list)
    # unwritable data path fails in enforce mode
    with pytest.raises(bootstrap.BootstrapCheckFailure):
        bootstrap.run_bootstrap_checks(
            {"path.data": "/proc/definitely/not/writable"}, enforce=True)


def test_sd_notify(tmp_path, monkeypatch):
    sock_path = str(tmp_path / "notify.sock")
    server = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    server.bind(sock_path)
    server.settimeout(5)
    monkeypatch.setenv("NOTIFY_SOCKET", sock_path)
    assert bootstrap.sd_notify("READY=1")
    assert server.recv(64) == b"READY=1"
    server.close()
    monkeypatch.delenv("NOTIFY_SOCKET")
    assert not bootstrap.sd_notify()  # no socket: no-op


def test_plugin_cli(tmp_path):
    src = tmp_path / "src" / "myplug"
    src.mkdir(parents=True)
    (src / "plugin.py").write_text(
        "from elasticsearch_tpu.plugins import Plugin\n"
        "class P(Plugin):\n    name = 'myplug'\n")
    (src / "plugin.json").write_text('{"name": "myplug", "version": "2.0"}')
    data = str(tmp_path / "data")
    env = {"PYTHONPATH": ".", "PATH": "/usr/bin:/bin"}

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "elasticsearch_tpu.plugin_cli", *args,
             "--data", data], capture_output=True, text=True, cwd=".",
            env=env)

    assert cli("install", str(src)).returncode == 0
    out = cli("list")
    assert "myplug 2.0" in out.stdout
    assert cli("install", str(src)).returncode == 1  # already installed
    assert cli("remove", "myplug").returncode == 0
    assert cli("list").stdout.strip() == ""
    assert cli("remove", "myplug").returncode == 1
