"""The bench.py watchdog: a hanging or unavailable backend must produce a
bounded-time diagnostic JSON line, never a stack trace or an indefinite hang
(round 2's official capture was lost to exactly that failure mode)."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_kills_hung_backend_within_timeout():
    bench = _load_bench()
    bench._PROBE_CODE = "import time; time.sleep(60)"
    ok, info = bench._probe_backend(timeout_s=2)
    assert not ok
    assert "hung" in info


def test_probe_reports_backend_error_tail():
    bench = _load_bench()
    bench._PROBE_CODE = (
        "raise RuntimeError(\"Unable to initialize backend 'axon': "
        "UNAVAILABLE\")")
    ok, info = bench._probe_backend(timeout_s=30)
    assert not ok
    assert "UNAVAILABLE" in info


def test_unavailable_backend_emits_diagnostic_json(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s: (False, "UNAVAILABLE"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # no CPU floor available either: the child fails too
    monkeypatch.setattr(bench, "_run_child",
                        lambda timeout_s, extra_env=None: (1, "", "down"))
    import pytest
    with pytest.raises(SystemExit) as e, _capture_stdout() as buf:
        bench.main()
    assert e.value.code == 1
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["error"] == "tpu_backend_unavailable"
    assert out["metric"] == bench.METRIC
    assert "last_known_good" in out


def test_unavailable_backend_falls_back_to_labeled_cpu_floor(monkeypatch):
    """Probe failure must produce a labeled CPU-floor measurement, not an
    evidence-free value: 0 (three of five past rounds went evidence-free)."""
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s: (False, "UNAVAILABLE"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    floor = json.dumps({"metric": bench.METRIC, "value": 432.1,
                        "unit": "qps", "n_docs": 131072})

    def fake_child(timeout_s, extra_env=None):
        assert extra_env and extra_env["JAX_PLATFORMS"] == "cpu"
        return 0, floor + "\n", ""

    monkeypatch.setattr(bench, "_run_child", fake_child)
    import pytest
    with pytest.raises(SystemExit) as e, _capture_stdout() as buf:
        bench.main()
    assert e.value.code == 1  # still not an official device capture
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["backend"] == "cpu_floor"
    assert out["value"] == 432.1
    assert out["error"] == "tpu_backend_unavailable"
    assert "last_known_good" in out


def test_child_crash_emits_diagnostic_json(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s: (True, "tpu"))
    monkeypatch.setattr(bench, "_run_child",
                        lambda timeout_s: (1, "", "boom"))
    import pytest
    with pytest.raises(SystemExit) as e, _capture_stdout() as buf:
        bench.main()
    assert e.value.code == 1
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["error"] == "bench_child_failed"


def test_timed_out_child_with_valid_result_counts_as_success(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s: (True, "tpu"))
    payload = json.dumps({"metric": bench.METRIC, "value": 12345.0})
    # rc=-1 models the watchdog killing a child that hung in teardown
    # after printing its measurement
    monkeypatch.setattr(bench, "_run_child",
                        lambda timeout_s: (-1, payload + "\n", "hung"))
    import pytest
    with pytest.raises(SystemExit) as e, _capture_stdout() as buf:
        bench.main()
    assert e.value.code == 0
    assert json.loads(buf.getvalue().strip().splitlines()[-1])["value"] == 12345.0


def test_child_json_line_is_forwarded(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s: (True, "tpu"))
    payload = json.dumps({"metric": bench.METRIC, "value": 99.0})
    monkeypatch.setattr(bench, "_run_child",
                        lambda timeout_s: (0, f"warning noise\n{payload}\n", ""))
    import pytest
    with pytest.raises(SystemExit) as e, _capture_stdout() as buf:
        bench.main()
    assert e.value.code == 0
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["value"] == 99.0


def _load_daemon():
    spec = importlib.util.spec_from_file_location(
        "bench_daemon", REPO / "bench_daemon.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_daemon_acquires_then_captures_labeled_tpu_rows(
        monkeypatch, tmp_path):
    """Probe flaps twice then succeeds: the daemon must keep polling and
    write the matrix the moment acquisition succeeds, every row labeled
    with its backend."""
    daemon = _load_daemon()
    attempts = iter([(False, "UNAVAILABLE"), (False, "probe hung"),
                     (True, "tpu v5e")])
    platform, errors = daemon.acquire_backend(
        max_wait_s=3600, probe=lambda timeout_s: next(attempts),
        sleep=lambda s: None)
    assert platform == "tpu v5e"
    assert len(errors) == 2

    rows = [{"config": "1_cosine_sift1m", "qps": 100.0},
            {"config": "3_hybrid_bm25_knn_rrf", "qps": 700.0}]
    monkeypatch.setattr(daemon, "run_matrix",
                        lambda extra_env, timeout_s: list(rows))
    out = tmp_path / "BENCH_MATRIX_r99.json"
    monkeypatch.setattr(daemon, "acquire_backend",
                        lambda *a, **k: ("tpu v5e", []))
    rc = daemon.main(["--round", "99", "--once", "--out", str(out)])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert lines[0]["_meta"]["backend"] == "tpu"
    assert all(r["backend"] == "tpu" for r in lines[1:])
    assert {r["config"] for r in lines[1:]} \
        == {"1_cosine_sift1m", "3_hybrid_bm25_knn_rrf"}


def test_daemon_dark_tunnel_emits_labeled_cpu_rows(monkeypatch, tmp_path):
    """No backend all round → the same configs land as clearly-labeled
    backend: cpu rows (never evidence-free, never mistakable for device
    numbers)."""
    daemon = _load_daemon()
    seen_env = {}

    def fake_run_matrix(extra_env, timeout_s):
        seen_env.update(extra_env)
        return [{"config": "3_hybrid_bm25_knn_rrf", "qps": 42.0,
                 "gate_500qps": False}]

    monkeypatch.setattr(daemon, "run_matrix", fake_run_matrix)
    monkeypatch.setattr(daemon, "acquire_backend",
                        lambda *a, **k: (None, ["attempt 1: UNAVAILABLE"]))
    out = tmp_path / "BENCH_MATRIX_r98.json"
    rc = daemon.main(["--round", "98", "--once", "--out", str(out)])
    assert rc == 0
    assert seen_env == {"JAX_PLATFORMS": "cpu", "BENCH_SMALL": "1"}
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert lines[0]["_meta"]["backend"] == "cpu"
    assert lines[0]["_meta"]["probe_errors"]
    assert lines[1]["backend"] == "cpu"
    assert "NOT a device number" in lines[1]["backend_note"]


def test_daemon_acquire_deadline_returns_none():
    daemon = _load_daemon()
    clock = {"t": 0.0}

    def sleep(s):
        clock["t"] += s

    platform, errors = daemon.acquire_backend(
        max_wait_s=0, probe=lambda timeout_s: (False, "dark"),
        sleep=sleep)
    assert platform is None
    assert errors


def test_daemon_keeps_partial_rows_on_matrix_hang(monkeypatch, tmp_path):
    """A hang after config N must still record configs 1..N (rows flush
    as they complete; the watchdog kills the child, not the evidence)."""
    daemon = _load_daemon()

    class FakeTimeout(Exception):
        pass

    import subprocess as sp

    def fake_run(*a, **k):
        e = sp.TimeoutExpired(cmd="bench_matrix", timeout=1)
        e.stdout = b'{"config": "1_cosine_sift1m", "qps": 5.0}\nhang'
        raise e

    monkeypatch.setattr(daemon.subprocess, "run", fake_run)
    rows = daemon.run_matrix({}, timeout_s=1)
    assert rows == [{"config": "1_cosine_sift1m", "qps": 5.0}]


def _load_bench_matrix():
    spec = importlib.util.spec_from_file_location(
        "bench_matrix", REPO / "bench_matrix.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_hybrid_bench_row_counts_plan_cache_hits_from_live_node(tmp_path):
    """The r06 record's `plan_cache_hits: 0` over 108 identical bodies:
    root-caused to the rows having been captured by a PRE-PR4 bench/
    engine snapshot (they lack the per-row `dispatch` delta PR 4 added,
    and that round's 6_sharded row still reports the pre-PR5 "needs >=2
    devices" skip) — the daemon runs whatever code is on disk at capture
    time, and the capture predated the plan-cache key fix. It was never
    a wrong-process/wrong-engine stats read: this test pins that the
    bench row's stats fields come from the SAME live node that served
    the queries, and that structurally-identical bodies actually hit."""
    import numpy as np

    from elasticsearch_tpu.node import Node

    bench_matrix = _load_bench_matrix()
    rng = np.random.default_rng(0)
    node = Node(str(tmp_path))
    node.create_index_with_templates("hy", mappings={"properties": {
        "body": {"type": "text"},
        "v": {"type": "dense_vector", "dims": 8}}})
    ops = []
    for i in range(40):
        ops.append({"index": {"_index": "hy", "_id": str(i)}})
        ops.append({"body": f"tok{i % 5} tok{i % 7}",
                    "v": rng.standard_normal(8).astype(float).tolist()})
    node.bulk(ops)
    node.indices.get("hy").force_merge()

    def body():
        return {"rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 100}},
                "query": {"match": {"body": "tok1 tok2"}},
                "knn": {"field": "v",
                        "query_vector":
                            rng.standard_normal(8).astype(float).tolist(),
                        "k": 10, "num_candidates": 10},
                "size": 10, "_source": False}

    n_queries = 8
    for _ in range(n_queries):
        assert node.search("hy", body())["hits"]["hits"]
    row = bench_matrix.hybrid_serving_stats(node)
    # identical SHAPES (different vectors/text) must share one plan:
    # exactly one miss, everything after it a hit — counted by the same
    # executor instance the searches went through
    assert row["plan_cache_misses"] == 1
    assert row["plan_cache_hits"] == n_queries - 1
    assert row["hybrid_batches"] >= 1
    assert row["rejected_429"] == 0
    # the tail-attribution split is present and self-consistent
    assert set(row["tail_ms"]) == {"queue_wait", "device", "hydrate"}
    assert row["tail_ms"]["device"] > 0
    assert set(row["sched"]) >= {"topups", "deadline_sheds",
                                 "overlap_hits"}
    node.close()


def test_closed_loop_row_scheduler_fields(tmp_path):
    """The 1cl/4cl rows' scheduler fields read from the live node's kNN
    batchers (`_nodes/stats indices.knn.scheduler`)."""
    import numpy as np

    from elasticsearch_tpu.node import Node

    bench_matrix = _load_bench_matrix()
    rng = np.random.default_rng(1)
    node = Node(str(tmp_path))
    node.create_index_with_templates("cl", mappings={"properties": {
        "v": {"type": "dense_vector", "dims": 8}}})
    ops = []
    for i in range(32):
        ops.append({"index": {"_index": "cl", "_id": str(i)}})
        ops.append({"v": rng.standard_normal(8).astype(float).tolist()})
    node.bulk(ops)
    node.indices.get("cl").refresh()
    for _ in range(4):
        node.search("cl", {
            "knn": {"field": "v",
                    "query_vector":
                        rng.standard_normal(8).astype(float).tolist(),
                    "k": 5, "num_candidates": 5},
            "size": 5, "_source": False})
    row = bench_matrix.knn_scheduler_stats(node)
    assert row["sched"]["batches"] >= 1
    assert set(row["tail_ms"]) == {"queue_wait", "dispatch", "finalize"}
    node.close()


class _capture_stdout:
    def __enter__(self):
        import io
        self._old = sys.stdout
        sys.stdout = buf = io.StringIO()
        return buf

    def __exit__(self, *exc):
        sys.stdout = self._old
        return False
