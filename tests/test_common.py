"""Tests for settings, serialization, and x-content."""

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.serialization import (
    NamedWriteable, NamedWriteableRegistry, StreamInput, StreamOutput,
)
from elasticsearch_tpu.common.settings import (
    Property, ScopedSettings, Setting, Settings, parse_byte_size, parse_time_value,
)
from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.common.xcontent import ObjectParser, XContentType


def test_settings_flatten_and_nest():
    s = Settings.of({"index": {"number_of_shards": 3, "refresh_interval": "1s"}})
    assert s.get("index.number_of_shards") == 3
    assert s.as_nested_dict()["index"]["refresh_interval"] == "1s"
    assert s.by_prefix("index.").get("number_of_shards") == 3


def test_typed_settings():
    shards = Setting.int_setting("index.number_of_shards", 1, Property.INDEX_SCOPE, min_value=1)
    s = Settings.of(index__number_of_shards="4")
    assert shards.get(s) == 4
    assert shards.get(Settings.EMPTY) == 1
    with pytest.raises(IllegalArgumentError):
        shards.get(Settings.of(index__number_of_shards="0"))


def test_time_and_bytes():
    assert parse_time_value("30s") == 30.0
    assert parse_time_value("500ms") == 0.5
    assert parse_time_value("-1") == -1
    assert parse_byte_size("2kb") == 2048
    assert parse_byte_size("1gb") == 1024 ** 3


def test_dynamic_settings_update():
    interval = Setting.time_setting("index.refresh_interval", "1s",
                                    Property.INDEX_SCOPE, Property.DYNAMIC)
    static = Setting.int_setting("index.number_of_shards", 1, Property.INDEX_SCOPE)
    scoped = ScopedSettings(Settings.EMPTY, [interval, static], Property.INDEX_SCOPE)
    seen = []
    scoped.add_settings_update_consumer(interval, seen.append)
    scoped.apply_settings(Settings.of({"index.refresh_interval": "5s"}))
    assert seen == [5.0]
    with pytest.raises(IllegalArgumentError):
        scoped.apply_settings(Settings.of({"index.number_of_shards": 2}))
    with pytest.raises(IllegalArgumentError):
        scoped.apply_settings(Settings.of({"bogus.key": 1}))


def test_stream_roundtrip():
    out = StreamOutput()
    out.write_vint(12345)
    out.write_zlong(-42)
    out.write_string("héllo")
    out.write_optional_string(None)
    out.write_generic({"a": [1, 2.5, True, None], "b": "x"})
    inp = StreamInput(out.bytes())
    assert inp.read_vint() == 12345
    assert inp.read_zlong() == -42
    assert inp.read_string() == "héllo"
    assert inp.read_optional_string() is None
    assert inp.read_generic() == {"a": [1, 2.5, True, None], "b": "x"}
    assert inp.remaining() == 0


class _Probe(NamedWriteable):
    def __init__(self, x):
        self.x = x

    def writeable_name(self):
        return "probe"

    def write_to(self, out):
        out.write_vint(self.x)


def test_named_writeable():
    reg = NamedWriteableRegistry()
    reg.register(_Probe, "probe", lambda inp: _Probe(inp.read_vint()))
    out = StreamOutput()
    out.write_named_writeable(_Probe(7))
    inp = StreamInput(out.bytes(), registry=reg)
    assert inp.read_named_writeable(_Probe).x == 7


def test_xcontent_json_and_cbor():
    doc = {"name": "tpu", "dims": 768, "v": [0.5, -1.25], "ok": True, "none": None}
    for ct in (XContentType.JSON, XContentType.CBOR):
        data = xcontent.dumps(doc, ct)
        assert xcontent.loads(data, ct) == doc
    assert xcontent.loads_auto(xcontent.dumps(doc, XContentType.CBOR)) == doc
    # YAML and SMILE are full codecs too (see test_xcontent_formats.py)
    for ct in (XContentType.YAML, XContentType.SMILE):
        assert xcontent.loads(xcontent.dumps(doc, ct), ct) == doc


def test_object_parser():
    class Req:
        def __init__(self):
            self.size = 10
            self.query = None

    p = ObjectParser("search", Req)
    p.declare_field("size", lambda o, v: setattr(o, "size", v))
    p.declare_field("query", lambda o, v: setattr(o, "query", v))
    r = p.parse({"size": 5, "query": {"match_all": {}}})
    assert r.size == 5 and r.query == {"match_all": {}}
    from elasticsearch_tpu.common.errors import ParsingError
    with pytest.raises(ParsingError):
        p.parse({"sizee": 5})
