"""The Wing & Gong linearizability checker itself (reference:
LinearizabilityCheckerTests.java): known-linearizable histories accepted,
known-violations rejected — most importantly a stale read served during a
partition, the anomaly invariant-based checks cannot see."""

import pytest

from elasticsearch_tpu.testing.linearizability import (
    TIMED_OUT, History, KeyedSpec, SequentialSpec, is_linearizable,
    visualize,
)


class RegisterSpec(SequentialSpec):
    """Integer register with write-returns-previous-value semantics (the
    reference harness's spec shape, AbstractCoordinatorTestCase:1459):
    a timed-out write is assumed applied; a timed-out read is a no-op."""

    def initial_state(self):
        return 0

    def next_state(self, state, inp, out):
        kind, val = inp
        if kind == "w":
            if out is TIMED_OUT or out == state:
                return val
            return None
        if out is TIMED_OUT or out == state:
            return state
        return None


class KeyedRegisterSpec(KeyedSpec, RegisterSpec):
    def get_key(self, inp):
        return inp[0]

    def get_value(self, inp):
        return inp[1]


def test_sequential_history_linearizable():
    h = History()
    w = h.invoke(("w", 7))
    h.respond(w, 0)
    r = h.invoke(("r", None))
    h.respond(r, 7)
    assert is_linearizable(RegisterSpec(), h)


def test_concurrent_overlap_linearizable():
    """read overlapping a write may see either old or new value."""
    for seen in (0, 42):
        h = History()
        w = h.invoke(("w", 42))
        r = h.invoke(("r", None))
        h.respond(r, seen)
        h.respond(w, 0)
        assert is_linearizable(RegisterSpec(), h), f"seen={seen}"


def test_stale_read_rejected():
    """THE target anomaly: a client writes 42 and gets the ack; a later,
    non-overlapping read returns the old value 0 (e.g. served by a deposed
    leader during a partition). No linearization order explains it."""
    h = History()
    w = h.invoke(("w", 42))
    h.respond(w, 0)           # write fully acknowledged...
    r = h.invoke(("r", None))
    h.respond(r, 0)           # ...yet a LATER read misses it
    assert not is_linearizable(RegisterSpec(), h), visualize(h)


def test_write_cycle_rejected():
    """Two acked writes each claiming the other's value as previous state
    form a cycle — impossible sequentially."""
    h = History()
    a = h.invoke(("w", 1))
    b = h.invoke(("w", 2))
    h.respond(a, 2)
    h.respond(b, 1)
    assert not is_linearizable(RegisterSpec(), h)


def test_timed_out_write_may_or_may_not_apply():
    """An unacked write completes as TIMED_OUT and may linearize last —
    a read seeing the OLD value afterwards is still linearizable."""
    h = History()
    h.invoke(("w", 9))        # never responds
    r = h.invoke(("r", None))
    h.respond(r, 0)
    assert is_linearizable(RegisterSpec(), h)


def test_keyed_partitioning():
    """Per-key sub-histories check independently: a violation on one key
    is found even when another key's history is fine."""
    h = History()
    w1 = h.invoke(("a", ("w", 1)))
    h.respond(w1, 0)
    r1 = h.invoke(("a", ("r", None)))
    h.respond(r1, 1)
    w2 = h.invoke(("b", ("w", 5)))
    h.respond(w2, 0)
    r2 = h.invoke(("b", ("r", None)))
    h.respond(r2, 0)          # stale read on key b
    spec = KeyedRegisterSpec()
    assert not is_linearizable(spec, h)
    h2 = History([e for e in h.events if e[2] != r2])
    r3 = h2.invoke(("b", ("r", None)))
    h2.respond(r3, 5)
    assert is_linearizable(spec, h2)


def test_remove_drops_definite_failures():
    h = History()
    w = h.invoke(("w", 3))
    h.remove(w)               # op provably never reached the system
    r = h.invoke(("r", None))
    h.respond(r, 0)
    assert is_linearizable(RegisterSpec(), h)


def test_malformed_history_raises():
    h = History()
    h.events.append(("response", 1, 99))
    with pytest.raises(ValueError):
        is_linearizable(RegisterSpec(), h)
