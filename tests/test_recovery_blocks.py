"""Durable elasticity foundation (`elasticsearch_tpu/recovery/`).

Pins the block-level durability contracts:
* collect/assemble round-trip — a flushed shard serialized into
  content-addressed blocks reassembles into an engine with identical
  docs, checkpoints and row layout, and an HONEST empty-translog
  checkpoint (a restored primary must never claim ops history it
  cannot replay);
* `BlockCache` digest discipline — a put whose bytes do not hash to
  the claimed digest is rejected; a blob corrupted at rest reads back
  as a miss (and is evicted), never as bad bytes;
* snapshot -> delete -> restore through a repository serves BYTE-
  identical responses with zero re-encoding: the codec extract counter
  for the packed field stays flat (blocks arrive via the seed sidecar)
  and knn results match exactly;
* the second snapshot of a churning index ships only blocks the
  repository has never seen (blob-count delta == blocks_shipped);
* a trained IVF layout restores into a fresh store without k-means:
  `ivf_restores` increments, `ivf_trains` stays 0, results identical.
"""

import os

import numpy as np
import pytest

from elasticsearch_tpu import columnar
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import (
    DenseVectorFieldMapper, MapperService,
)
from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.recovery.blocks import block_digest
from elasticsearch_tpu.recovery.peer import BlockCache
from elasticsearch_tpu.recovery.snapshot import (
    assemble_shard, collect_shard_blocks,
)
from elasticsearch_tpu.vectors.store import VectorStoreShard

MAPPING = {
    "properties": {
        "title": {"type": "text", "analyzer": "standard"},
        "tag": {"type": "keyword"},
        "views": {"type": "long"},
    }
}

DIMS = 32


# ---------------------------------------------------------------------------
# collect/assemble round-trip at the engine level
# ---------------------------------------------------------------------------

def test_collect_assemble_roundtrip(tmp_path):
    src = Engine(str(tmp_path / "src"), MapperService(MAPPING))
    for i in range(20):
        src.index(str(i), {"title": f"doc number {i}", "tag": f"t{i % 3}",
                           "views": i})
    src.refresh()
    for i in range(0, 20, 5):
        src.delete(str(i))
    src.flush()
    entries, payloads, meta = collect_shard_blocks(src)
    # every entry addresses a payload and the digest matches the bytes
    for e in entries:
        assert block_digest(payloads[e["digest"]]) == e["digest"]
        assert e["size"] == len(payloads[e["digest"]])

    dst_path = str(tmp_path / "dst")
    out = assemble_shard(dst_path, entries, meta, payloads.__getitem__)
    assert out["segments"] >= 1 and out["blocks_total"] == len(entries)

    dst = Engine(dst_path, MapperService(MAPPING))
    try:
        assert dst.doc_count() == src.doc_count() == 16
        assert dst.local_checkpoint == src.local_checkpoint
        for i in range(20):
            a, b = src.get(str(i)), dst.get(str(i))
            if a is None:
                assert b is None
            else:
                assert b["_source"] == a["_source"]
                assert b["_version"] == a["_version"]
        # the restored translog checkpoint is HONEST: an empty translog
        # cannot claim it can replay history from seq_no 0
        assert not dst.can_replay_from(0)
        assert dst.can_replay_from(dst.local_checkpoint + 1)
    finally:
        dst.close()
        src.close()


def test_assemble_rejects_corrupt_block(tmp_path):
    src = Engine(str(tmp_path / "src"), MapperService(MAPPING))
    src.index("1", {"title": "x"})
    src.flush()
    entries, payloads, meta = collect_shard_blocks(src)
    src.close()
    bad = dict(payloads)
    victim = entries[0]["digest"]
    bad[victim] = bad[victim][:-1] + b"\x00"
    with pytest.raises(ValueError, match="digest verification"):
        assemble_shard(str(tmp_path / "dst"), entries, meta, bad.__getitem__)


# ---------------------------------------------------------------------------
# BlockCache digest discipline
# ---------------------------------------------------------------------------

def test_block_cache_put_get_roundtrip(tmp_path):
    cache = BlockCache(str(tmp_path / "blocks"))
    data = b"some block bytes"
    digest = block_digest(data)
    assert not cache.has(digest) and cache.get(digest) is None
    cache.put(digest, data)
    assert cache.has(digest)
    assert cache.get(digest) == data
    assert digest in cache.held()
    cache.evict(digest)
    assert not cache.has(digest)


def test_block_cache_rejects_mismatched_put(tmp_path):
    cache = BlockCache(str(tmp_path / "blocks"))
    with pytest.raises(ValueError):
        cache.put(block_digest(b"expected"), b"different")
    assert cache.held() == set()


def test_block_cache_corrupt_at_rest_reads_as_miss(tmp_path):
    cache = BlockCache(str(tmp_path / "blocks"))
    data = b"block payload"
    digest = block_digest(data)
    cache.put(digest, data)
    # flip a byte on disk behind the cache's back
    path = os.path.join(str(tmp_path / "blocks"), digest)
    with open(path, "wb") as f:
        f.write(b"rotten")
    assert cache.get(digest) is None          # corrupt -> miss, not bytes
    assert not os.path.exists(path)           # and the corpse is evicted


def test_block_cache_rejects_traversal_keys(tmp_path):
    cache = BlockCache(str(tmp_path / "blocks"))
    for key in ("../escape", "not-hex!", ""):
        with pytest.raises(ValueError):
            cache.put(key, b"x")


# ---------------------------------------------------------------------------
# node-level: snapshot -> delete -> restore, byte-identical, zero re-encode
# ---------------------------------------------------------------------------

def _vec_mapping(otype="int4_flat"):
    return {"properties": {
        "title": {"type": "text"},
        "v": {"type": "dense_vector", "dims": DIMS, "similarity": "cosine",
              "index_options": {"type": otype}},
    }}


def _bulk_vectors(node, index, n, base=0, seed=5):
    rng = np.random.default_rng(seed + base)
    ops = []
    for i in range(n):
        ops.append({"index": {"_index": index, "_id": str(base + i)}})
        ops.append({"title": f"doc {base + i}",
                    "v": rng.standard_normal(DIMS).astype(np.float32)
                    .tolist()})
    node.bulk(ops)
    node.indices.get(index).refresh()


def _knn(node, index, seed=99):
    q = np.random.default_rng(seed).standard_normal(DIMS).tolist()
    body = {"knn": {"field": "v", "query_vector": q, "k": 5,
                    "num_candidates": 32}, "size": 5}
    resp = node.search(index, body)
    return [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]


def test_snapshot_delete_restore_byte_identical_zero_reencode(tmp_path):
    node = Node(str(tmp_path / "data"))
    try:
        node.create_index_with_templates("src", mappings=_vec_mapping())
        _bulk_vectors(node, "src", 64)
        before = _knn(node, "src")
        assert len(before) == 5

        node.snapshots.put_repository("mem", {
            "type": "memory", "settings": {"location": "dur-mem"}})
        node.snapshots.create_snapshot("mem", "s1", {"indices": "src"})
        node.indices.delete_index("src")

        stats0 = columnar.STORE.stats()
        enc0 = stats0["fields"].get("v:vector_enc", {}).get("extracts", 0)
        seeds0 = stats0["seeds"]

        node.snapshots.restore_snapshot("mem", "s1", {"indices": "src"})
        after = _knn(node, "src")

        # byte-identical serving: same hits, same scores, same order
        assert after == before
        stats1 = columnar.STORE.stats()
        enc1 = stats1["fields"].get("v:vector_enc", {}).get("extracts", 0)
        assert enc1 == enc0, "restore must not re-encode packed vectors"
        assert stats1["seeds"] > seeds0, \
            "restored encoded blocks arrive via the seed sidecar"
        # restore accounted at block level for `_recovery`
        bstats = node.indices.get("src").recovery_block_stats
        assert bstats and all(st["blocks_total"] > 0
                              for st in bstats.values())
    finally:
        node.close()


def test_second_snapshot_ships_only_new_blocks(tmp_path):
    node = Node(str(tmp_path / "data"))
    try:
        node.create_index_with_templates("churn", mappings=_vec_mapping())
        _bulk_vectors(node, "churn", 48)
        node.snapshots.put_repository("mem", {
            "type": "memory", "settings": {"location": "churn-mem"}})
        node.snapshots.create_snapshot("mem", "s1", {"indices": "churn"})
        repo = node.snapshots.get_repository("mem")
        blobs1 = set(repo.store.list_blobs("blobs/"))

        _bulk_vectors(node, "churn", 16, base=48)      # delta ingest
        node.snapshots.create_snapshot("mem", "s2", {"indices": "churn"})
        blobs2 = set(repo.store.list_blobs("blobs/"))

        m1 = repo.get_manifest("s1")["indices"]["churn"]["shards"]["0"]
        m2 = repo.get_manifest("s2")["indices"]["churn"]["shards"]["0"]
        d1 = {e["digest"] for e in m1["blocks"]}
        d2 = {e["digest"] for e in m2["blocks"]}

        # incrementality: s2 uploaded exactly the blocks s1 didn't have
        assert m2["stats"]["blocks_shipped"] == len(blobs2) - len(blobs1)
        assert m2["stats"]["blocks_shipped"] == len(d2 - d1)
        assert m2["stats"]["blocks_reused"] == len(d2 & d1)
        assert m2["stats"]["blocks_reused"] > 0, \
            "sealed generations from s1 must be reused, not re-shipped"
    finally:
        node.close()


# ---------------------------------------------------------------------------
# store-level: trained IVF layout restores without k-means
# ---------------------------------------------------------------------------

def _seg(seg_id, base, mat):
    n = mat.shape[0]
    return Segment(
        seg_id=seg_id, base=base, num_docs=n, postings={},
        field_lengths={}, total_terms={}, doc_values={},
        vectors={"v": (mat, np.ones(n, dtype=bool))},
        ids=[f"d{base + i}" for i in range(n)], sources=[None] * n,
        seq_nos=np.arange(base, base + n, dtype=np.int64))


def _mapper(otype):
    return DenseVectorFieldMapper("v", {
        "type": "dense_vector", "dims": DIMS, "similarity": "cosine",
        "index_options": {"type": otype}})


def _store():
    return VectorStoreShard(host_mirror_max_bytes=0,
                            segments_background_merge=False)


def test_ivf_layout_restore_skips_training():
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((8, DIMS)).astype(np.float32) * 2.0
    mat = (centers[rng.integers(0, 8, size=900)]
           + 0.4 * rng.standard_normal((900, DIMS)).astype(np.float32))
    reader = ShardReader([SegmentView(_seg(0, 0, mat))])
    mappers = {"v": _mapper("int4_ivf")}

    trained = _store()
    trained.sync(reader, mappers)
    assert trained.knn_stats["ivf_trains"] == 1
    assert trained.knn_stats["ivf_restores"] == 0
    layouts = trained.export_ivf_layout()
    assert "v" in layouts and layouts["v"]["trained_on"] > 0

    restored = _store()
    restored.restore_ivf_layout(layouts)
    restored.sync(reader, mappers)
    assert restored.knn_stats["ivf_trains"] == 0, \
        "restore must re-place rows into snapshotted centroids, not retrain"
    assert restored.knn_stats["ivf_restores"] == 1

    q = mat[3] + 0.1 * rng.standard_normal(DIMS).astype(np.float32)
    rows_a, scores_a = trained.search("v", q, 10)
    rows_b, scores_b = restored.search("v", q, 10)
    np.testing.assert_array_equal(rows_a, rows_b)
    np.testing.assert_allclose(scores_a, scores_b, rtol=1e-6)


# ---------------------------------------------------------------------------
# snapshot stream limiter: bounded concurrency + per-node byte throttle
# ---------------------------------------------------------------------------

class _CountingRepo:
    """In-memory repo that records upload concurrency high-water."""

    def __init__(self):
        import threading
        self.blobs = {}
        self._lock = threading.Lock()
        self._active = 0
        self.max_active = 0

    def has_blob(self, digest):
        return digest in self.blobs

    def put_bytes(self, data):
        import time
        with self._lock:
            self._active += 1
            self.max_active = max(self.max_active, self._active)
        time.sleep(0.02)  # widen the overlap window
        with self._lock:
            self.blobs[block_digest(data)] = data
            self._active -= 1


def test_stream_limiter_token_bucket_sleeps_out_deficit():
    import time
    from elasticsearch_tpu.recovery.snapshot import SnapshotStreamLimiter
    lim = SnapshotStreamLimiter(max_streams=1, max_bytes_per_sec=100_000)
    lim.throttle(100_000)            # consumes the initial 1s burst
    t0 = time.monotonic()
    lim.throttle(15_000)             # ~150ms deficit at 100KB/s
    waited = time.monotonic() - t0
    assert waited >= 0.1
    assert lim.stats["blocks_throttled"] == 1
    assert lim.stats["throttle_time_in_millis"] > 0


def test_stream_limiter_reapplying_same_rate_keeps_spent_allowance():
    from elasticsearch_tpu.recovery.snapshot import SnapshotStreamLimiter
    lim = SnapshotStreamLimiter(max_streams=1, max_bytes_per_sec=100_000)
    lim.throttle(100_000)
    # every shard upload re-reads cluster settings: the SAME rate must
    # not refund the spent bucket...
    lim.configure(max_bytes_per_sec=100_000)
    assert lim._allowance <= 1_000
    # ...but a CHANGED rate restarts the bucket full
    lim.configure(max_bytes_per_sec=50_000)
    assert lim._allowance == 50_000.0


def test_stream_limiter_configure_from_settings_parses_units():
    from elasticsearch_tpu.recovery.snapshot import SnapshotStreamLimiter
    lim = SnapshotStreamLimiter()
    lim.configure_from_settings({"snapshot.max_bytes_per_sec": "2mb",
                                 "snapshot.max_concurrent_streams": "3"})
    assert lim.max_bytes_per_sec == 2 * 1024 * 1024
    assert lim.max_streams == 3
    # garbage values are ignored, not fatal (snapshots must not break on
    # a bad setting)
    lim.configure_from_settings({"snapshot.max_bytes_per_sec": "alot"})
    assert lim.max_bytes_per_sec == 2 * 1024 * 1024


def test_snapshot_shard_uploads_concurrently_under_limiter(tmp_path):
    from elasticsearch_tpu.recovery.snapshot import (
        SnapshotStreamLimiter, snapshot_shard)
    src = Engine(str(tmp_path / "src"), MapperService(MAPPING))
    try:
        # two refresh generations -> >=3 blocks (2 segments + ledger)
        for i in range(10):
            src.index(str(i), {"title": f"doc {i}", "tag": "a", "views": i})
        src.refresh()
        for i in range(10, 20):
            src.index(str(i), {"title": f"doc {i}", "tag": "b", "views": i})
        src.flush()
        repo = _CountingRepo()
        lim = SnapshotStreamLimiter(max_streams=3, max_bytes_per_sec=0)
        entry = snapshot_shard(repo, src, limiter=lim)
        assert entry["stats"]["blocks_shipped"] >= 3
        assert repo.max_active >= 2, "uploads never overlapped"
        assert lim.stats["max_concurrent_streams"] >= 2
        # every manifest digest landed in the repo
        for e in entry["blocks"]:
            assert repo.has_blob(e["digest"])
        # second snapshot of identical state ships nothing
        entry2 = snapshot_shard(repo, src, limiter=lim)
        assert entry2["stats"]["blocks_shipped"] == 0
        assert entry2["stats"]["blocks_reused"] > 0
    finally:
        src.close()


def test_snapshot_stream_stats_ride_nodes_stats(tmp_path):
    """`_nodes/stats indices.recovery.snapshot_streams` surfaces the
    node-wide limiter's counters and configuration."""
    import json
    node = Node(str(tmp_path / "data"))
    try:
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController
        rc = RestController()
        register_all(rc, node)
        st, body = rc.dispatch("GET", "/_nodes/stats", {}, b"",
                               "application/json")
        assert st == 200
        node_stats = next(iter(body["nodes"].values()))
        streams = node_stats["indices"]["recovery"]["snapshot_streams"]
        for key in ("throttle_time_in_millis", "blocks_throttled",
                    "blocks_uploaded", "bytes_uploaded",
                    "max_concurrent_streams", "max_streams",
                    "max_bytes_per_sec"):
            assert key in streams, key
        json.dumps(streams)
    finally:
        node.close()
