"""Durable gateway: full-cluster restart recovers committed metadata with
monotonic terms (PersistedClusterStateService / GatewayMetaState analog)."""

import json
import os

from elasticsearch_tpu.cluster.gateway import FilePersistedState
from elasticsearch_tpu.cluster.state import ClusterState, VotingConfiguration

from test_multi_node import TestCluster


def _mk_state(term=3, version=7):
    return ClusterState(
        term=term, version=version,
        metadata={"idx": {"settings": {"index.number_of_shards": 1},
                          "mappings": {"properties": {"f": {"type": "long"}}}}},
        last_committed_config=VotingConfiguration(["a", "b", "c"]),
        last_accepted_config=VotingConfiguration(["a", "b", "c"]))


def test_persist_and_recover(tmp_path):
    p = FilePersistedState(str(tmp_path))
    p.set_term(5)
    p.set_last_accepted(_mk_state())
    # recover from a brand-new object
    r = FilePersistedState(str(tmp_path))
    assert r.current_term == 5
    assert r.last_accepted.version == 7
    assert r.last_accepted.metadata["idx"]["mappings"]["properties"]["f"]["type"] == "long"
    assert r.last_accepted.last_committed_config.node_ids == {"a", "b", "c"}


def test_initial_state_ignored_once_booted(tmp_path):
    p = FilePersistedState(str(tmp_path), initial_state=_mk_state(version=1))
    p.set_term(9)
    p.set_last_accepted(_mk_state(term=9, version=42))
    r = FilePersistedState(str(tmp_path), initial_state=_mk_state(version=1))
    assert r.current_term == 9 and r.last_accepted.version == 42


def test_torn_write_falls_back_to_previous_generation(tmp_path):
    p = FilePersistedState(str(tmp_path))
    p.set_term(2)
    p.set_last_accepted(_mk_state(term=2, version=10))
    p.set_last_accepted(_mk_state(term=2, version=11))
    # corrupt the newest generation file (torn write)
    gens = sorted(os.listdir(p.dir), key=lambda n: int(n[6:-5]))
    newest = os.path.join(p.dir, gens[-1])
    with open(newest, "r+b") as f:
        data = f.read()
        f.seek(0)
        f.write(data[: len(data) // 2])
        f.truncate()
    r = FilePersistedState(str(tmp_path))
    assert r.current_term == 2
    assert r.last_accepted.version == 10  # previous generation


def test_corrupt_crc_detected(tmp_path):
    p = FilePersistedState(str(tmp_path))
    p.set_last_accepted(_mk_state(version=5))
    gens = sorted(os.listdir(p.dir), key=lambda n: int(n[6:-5]))
    newest = os.path.join(p.dir, gens[-1])
    with open(newest) as f:
        wrapper = json.load(f)
    wrapper["doc"]["state"]["version"] = 999  # tamper without fixing crc
    with open(newest, "w") as f:
        json.dump(wrapper, f)
    r = FilePersistedState(str(tmp_path))
    assert r.last_accepted.version != 999


def test_full_cluster_restart_recovers_metadata_and_data(tmp_path):
    c = TestCluster(tmp_path, n_nodes=3, seed=11)
    assert c.run_until(lambda: c.master() is not None)
    c.any_node().client_create_index(
        "keep", settings={"index.number_of_shards": 1,
                          "index.number_of_replicas": 1},
        mappings={"properties": {"t": {"type": "text"},
                                 "n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("keep"))
    w = c.any_node()
    for i in range(10):
        r = c.call(w.client_write, "keep",
                   {"type": "index", "id": str(i),
                    "source": {"t": f"hello {i}", "n": i}})
        assert r["result"] == "created"
    term_before = c.any_node().cluster_state.term
    for n in c.nodes.values():
        n.stop()

    # whole-cluster restart: same data paths, fresh transport + scheduler
    c2 = TestCluster(tmp_path, n_nodes=3, seed=23)
    assert c2.run_until(lambda: c2.master() is not None), "no master after restart"
    state = c2.master().cluster_state
    # committed metadata survived
    assert "keep" in state.metadata, "index metadata lost on restart"
    assert state.metadata["keep"]["mappings"]["properties"]["n"]["type"] == "long"
    # terms monotonic across the restart
    assert state.term > term_before
    # shard data recovered from the on-disk engines once shards restart
    assert c2.run_until(lambda: c2.all_started("keep")), "shards did not restart"
    for n in c2.nodes.values():
        n.refresh_all()
    resp = c2.call(c2.any_node().client_search, "keep",
                   {"query": {"match_all": {}}, "size": 20})
    assert resp["hits"]["total"]["value"] == 10, resp["hits"]["total"]
    for n in c2.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_generation_resumes_past_unreadable_files(tmp_path):
    # if the highest generations are unreadable, new writes must supersede
    # them (not be deleted by the retention sweep keeping corrupt files)
    p = FilePersistedState(str(tmp_path))
    p.set_term(4)
    p.set_last_accepted(_mk_state(term=4, version=2))
    for name in os.listdir(p.dir):
        with open(os.path.join(p.dir, name), "w") as f:
            f.write("garbage")
    r = FilePersistedState(str(tmp_path))
    assert r.current_term == 0  # nothing readable
    r.set_term(1)
    r2 = FilePersistedState(str(tmp_path))
    assert r2.current_term == 1, "fresh durable state was lost to the sweep"
