"""Device aggregations rung 2: calendar intervals, composite sub-agg
trees, HLL cardinality, and the measured cost router.

Same two contracts as test_device_aggs.py — json-identical parity with
the host walkers (final AND distributed-partial mode) and a closed
dispatch grid (zero steady-state recompiles under strict mode) — over
the rung-2 surface:

* calendar date_histograms (month/quarter/year/week, timezone-shifted
  days across DST transitions, leap years) via the boundary-table
  `aggs.cal_*` kernels;
* multi-level sub-agg trees (3 deep, empty parents, min_doc_count: 0)
  via composite-id `aggs.tree_*` boards;
* cardinality via `aggs.hll_board` register boards whose packed `$p`
  states merge byte-identically with the host's on skewed shard splits;
* the measured cost router (`routed_host_cheaper`), fallback-reason doc
  totals, and the observed-cardinality / warmup-clamp satellites.
"""

import json
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.ops import aggs as aggs_ops
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.search.agg_partials import (
    compute_partial_aggs, finalize_aggs, merge_partial_aggs,
)
from elasticsearch_tpu.search.agg_plan import AggEngine, CostRouter
from elasticsearch_tpu.search.aggregations import compute_aggs
from elasticsearch_tpu.search.queries import SearchContext

MAPPING = {"properties": {
    "cat": {"type": "keyword"},
    "sub": {"type": "keyword"},
    "tags": {"type": "keyword"},
    "v": {"type": "long"},
    "price": {"type": "double"},
    "ts": {"type": "date"},       # weekly spread over ~7 years
    "ts_dst": {"type": "date"},   # hourly spread across DST transitions
}}

# 2019-01-01; weekly steps cross leap day 2020-02-29 and leap year 2024
TS0 = 1_546_300_800_000
# 2020-03-07; hourly steps cross the America/New_York spring-forward
# (2020-03-08 02:00) — and, offset by docs, the 2020-11-01 fall-back
DST0 = 1_583_550_000_000


def _index_docs(e, n=360):
    for i in range(n):
        doc = {"cat": ["red", "green", "blue"][i % 3],
               "sub": ["x", "y"][i % 2],
               "tags": ["a", "b"] if i % 5 == 0 else "c",
               "v": i,
               "ts": TS0 + i * 7 * 86_400_000,
               "ts_dst": DST0 + i * 3_600_000
               + (20_000_000_000 if i % 2 else 0)}
        if i % 7 != 0:
            doc["price"] = i * 0.5
        if i % 11 == 0:
            del doc["cat"]
        e.index(str(i), doc)
    e.refresh()


@pytest.fixture(scope="module")
def ctx():
    e = Engine(tempfile.mkdtemp() + "/shard", MapperService(MAPPING))
    _index_docs(e)
    yield SearchContext(e.acquire_searcher(), e.mapper_service)
    e.close()


@pytest.fixture()
def engine(ctx):
    return AggEngine(ctx.mapper_service)


def _rows(ctx, frac=3):
    rows = ctx.all_rows()
    return rows[rows % frac != 0]


def _json(x):
    return json.dumps(x, sort_keys=True, default=str)


# ---------------------------------------------------------------------------
# calendar intervals
# ---------------------------------------------------------------------------


CAL_SPECS = [
    {"d": {"date_histogram": {"field": "ts",
                              "calendar_interval": "month"}}},
    {"d": {"date_histogram": {"field": "ts",
                              "calendar_interval": "quarter",
                              "format": "yyyy-MM-dd"}}},
    {"d": {"date_histogram": {"field": "ts",
                              "calendar_interval": "year"}}},
    {"d": {"date_histogram": {"field": "ts",
                              "calendar_interval": "week"}}},
    # leap-year February boundaries under a real IANA zone
    {"d": {"date_histogram": {"field": "ts", "calendar_interval": "month",
                              "time_zone": "America/New_York"}}},
    # tz-shifted days across the spring-forward (23h day) and fall-back
    # (25h day) transitions: boundary table, not fixed 24h arithmetic
    {"d": {"date_histogram": {"field": "ts_dst",
                              "calendar_interval": "day",
                              "time_zone": "America/New_York"}}},
    {"d": {"date_histogram": {"field": "ts_dst",
                              "calendar_interval": "day",
                              "time_zone": "Europe/Berlin"}}},
    {"d": {"date_histogram": {"field": "ts_dst",
                              "calendar_interval": "hour",
                              "time_zone": "America/New_York"}}},
    # offset + sub-metrics ride the same boards as fixed intervals
    {"d": {"date_histogram": {"field": "ts", "calendar_interval": "month",
                              "offset": "+6h"},
           "aggs": {"s": {"stats": {"field": "v"}}}}},
]


@pytest.mark.parametrize("spec", CAL_SPECS)
def test_calendar_final_parity(ctx, engine, spec):
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None, "expected a device-eligible plan"
    dev, prof = got
    assert _json(dev) == _json(host)
    assert all(n["engine"].startswith("device") for n in prof["nodes"])


@pytest.mark.parametrize("spec", CAL_SPECS[:5])
def test_calendar_partial_parity(ctx, engine, spec):
    rows = ctx.all_rows()
    n = len(rows)
    splits = [rows[: n // 6], rows[n // 6: n // 2], rows[n // 2:]]
    hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
    hm = hp[0]
    for p in hp[1:]:
        hm = merge_partial_aggs(hm, p, spec)
    dp = []
    for r in splits:
        got = engine.compute(ctx, r, spec, partial=True)
        assert got is not None
        dp.append(got[0])
    dm = dp[0]
    for p in dp[1:]:
        dm = merge_partial_aggs(dm, p, spec)
    assert _json(finalize_aggs(dm, spec)) == _json(finalize_aggs(hm, spec))


def test_calendar_empty_match_set(ctx, engine):
    rows = np.zeros(0, dtype=np.int64)
    for spec in CAL_SPECS[:3]:
        host = compute_aggs(ctx, rows, spec)
        got = engine.compute(ctx, rows, spec, partial=False)
        assert got is not None
        assert _json(got[0]) == _json(host)


# ---------------------------------------------------------------------------
# composite sub-agg trees
# ---------------------------------------------------------------------------


TREE_SPECS = [
    # 2-level: terms > terms with metric leaves at both depths
    {"t": {"terms": {"field": "cat"},
           "aggs": {"mx": {"max": {"field": "v"}},
                    "by_sub": {"terms": {"field": "sub"},
                               "aggs": {"s": {"stats": {"field": "v"}}}}}}},
    # 3-level: terms > terms > histogram, metric at the leaf
    {"t": {"terms": {"field": "cat"},
           "aggs": {"by_sub": {"terms": {"field": "sub"},
                               "aggs": {"h": {"histogram": {
                                   "field": "v", "interval": 100},
                                   "aggs": {"m": {"min": {
                                       "field": "price"}}}}}}}}},
    # calendar child under a terms parent (boundary table inside a tree)
    {"t": {"terms": {"field": "cat"},
           "aggs": {"q": {"date_histogram": {
               "field": "ts", "calendar_interval": "quarter"}}}}},
    # min_doc_count: 0 at BOTH levels — zero-count parents still emit
    # their children's full zero-count universe
    {"t": {"terms": {"field": "cat", "min_doc_count": 0},
           "aggs": {"by_sub": {"terms": {"field": "sub",
                                         "min_doc_count": 0}}}}},
    # missing-bucket parent merges lanes before children decompose
    {"t": {"terms": {"field": "cat", "missing": "zzz"},
           "aggs": {"by_sub": {"terms": {"field": "sub"},
                               "aggs": {"c": {"value_count": {
                                   "field": "v"}}}}}}},
    # histogram parent with terms child + extended_bounds gap buckets
    {"h": {"histogram": {"field": "v", "interval": 120,
                         "extended_bounds": {"min": -120, "max": 600}},
           "aggs": {"by_sub": {"terms": {"field": "sub"}}}}},
    # meta on a tree node (final mode attaches it at the top level)
    {"t": {"terms": {"field": "cat"}, "meta": {"who": "dash"},
           "aggs": {"by_sub": {"terms": {"field": "sub"}}}}},
]


@pytest.mark.parametrize("spec", TREE_SPECS)
def test_tree_final_parity(ctx, engine, spec):
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None, "expected a device-eligible plan"
    dev, prof = got
    assert _json(dev) == _json(host)
    assert all(n["engine"].startswith("device") for n in prof["nodes"])


def test_tree_empty_parent_buckets(ctx, engine):
    """Rows filtered so one whole cat value has zero matches: its parent
    bucket (min_doc_count: 0) must still carry the children's zero-count
    universes, exactly like the host's empty-rows recursion."""
    rows = ctx.all_rows()
    rows = rows[rows % 3 != 0]  # cat 'red' rides i % 3 == 0 docs only
    spec = {"t": {"terms": {"field": "cat", "min_doc_count": 0},
                  "aggs": {"by_sub": {"terms": {"field": "sub",
                                                "min_doc_count": 0},
                                      "aggs": {"s": {"stats": {
                                          "field": "v"}}}}}}}
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None
    assert _json(got[0]) == _json(host)


def test_tree_partial_parity(ctx, engine):
    rows = ctx.all_rows()
    n = len(rows)
    splits = [rows[: n // 8], rows[n // 8: n // 2], rows[n // 2:]]
    for spec in TREE_SPECS[:4]:
        hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
        hm = hp[0]
        for p in hp[1:]:
            hm = merge_partial_aggs(hm, p, spec)
        dp = []
        for r in splits:
            got = engine.compute(ctx, r, spec, partial=True)
            assert got is not None
            dp.append(got[0])
        dm = dp[0]
        for p in dp[1:]:
            dm = merge_partial_aggs(dm, p, spec)
        assert _json(finalize_aggs(dm, spec)) == \
            _json(finalize_aggs(hm, spec))


def test_tree_too_deep_falls_back(ctx, engine):
    spec = {"t": {"terms": {"field": "cat"}, "aggs": {
        "l2": {"terms": {"field": "sub"}, "aggs": {
            "l3": {"histogram": {"field": "v", "interval": 100}, "aggs": {
                "l4": {"terms": {"field": "sub"}}}}}}}}}
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    if got is not None:
        assert _json(got[0]) == _json(host)
    assert "tree_too_deep" in engine.plan_for(spec).nodes["t"].host_reason


# ---------------------------------------------------------------------------
# HLL cardinality
# ---------------------------------------------------------------------------


CARD_SPECS = [
    {"c": {"cardinality": {"field": "cat"}}},
    {"c": {"cardinality": {"field": "v"}}},
    {"c": {"cardinality": {"field": "cat", "missing": "none"}}},
    {"t": {"terms": {"field": "cat"},
           "aggs": {"cd": {"cardinality": {"field": "sub"}},
                    "cv": {"cardinality": {"field": "v"}}}}},
    {"d": {"date_histogram": {"field": "ts", "calendar_interval": "year"},
           "aggs": {"cd": {"cardinality": {"field": "cat"}}}}},
]


@pytest.mark.parametrize("spec", CARD_SPECS)
def test_cardinality_final_parity(ctx, engine, spec):
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None, "expected a device-eligible plan"
    dev, prof = got
    assert _json(dev) == _json(host)
    assert all(n["engine"].startswith("device") for n in prof["nodes"])


def test_hll_merge_parity_skewed_splits(ctx, engine):
    """Device HLL register boards pack into `$p` states byte-identical
    to the host's, so merge_partial_aggs composes device and host
    partials interchangeably — including tiny and lopsided shards."""
    rows = ctx.all_rows()
    n = len(rows)
    for cuts in ([5, 20], [1, n - 1], [n // 10, n // 2]):
        splits = np.split(rows, cuts)
        for spec in CARD_SPECS:
            hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
            dp = []
            for r in splits:
                got = engine.compute(ctx, r, spec, partial=True)
                assert got is not None
                dp.append(got[0])
            # cross-merge: host state folded into device state
            hm, dm = hp[0], dp[0]
            for p in hp[1:]:
                hm = merge_partial_aggs(hm, p, spec)
            for p in dp[1:]:
                dm = merge_partial_aggs(dm, p, spec)
            assert _json(dp[0]) == _json(hp[0])  # states, not just finals
            assert _json(finalize_aggs(dm, spec)) == \
                _json(finalize_aggs(hm, spec))


def test_cardinality_negative_precision_raises_like_host(ctx, engine):
    spec = {"c": {"cardinality": {"field": "cat",
                                  "precision_threshold": -1}}}
    rows = _rows(ctx)
    with pytest.raises(IllegalArgumentError, match="precisionThreshold"):
        compute_aggs(ctx, rows, spec)
    with pytest.raises(IllegalArgumentError, match="precisionThreshold"):
        engine.compute(ctx, rows, spec, partial=False)


# ---------------------------------------------------------------------------
# cost router + fallback-stat satellites
# ---------------------------------------------------------------------------


def test_cost_router_prior_routes_tiny_corpus_host():
    r = CostRouter()
    # 100 matched docs: host walker estimate beats the fixed dispatch
    # floor even with margin — prior routes host
    assert r.decide("terms", 100, 1024) == "host"
    # huge corpus: device wins on the prior
    assert r.decide("terms", 1_000_000, 1 << 20) == "device"


def test_cost_router_measurements_flip_decision():
    r = CostRouter()
    # measured: device is 10x faster than the host walker at this size
    for _ in range(8):
        r.observe_device("terms", 50_000)
        r.observe_host("terms", 500_000, 100)
    assert r.decide("terms", 100, 1024) == "device"
    # measured the other way: host wins, device only via reprobe cadence
    for _ in range(32):
        r.observe_device("hist", 5_000_000)
        r.observe_host("hist", 100_000, 1_000)
    decisions = [r.decide("hist", 1_000, 1024) for _ in range(CostRouter.REPROBE)]
    assert "probe" in decisions
    assert decisions.count("host") == CostRouter.REPROBE - 1
    snap = r.snapshot()
    assert "hist" in snap["device_ns"] and "hist" in snap["host_ns_per_doc"]


def test_cost_router_persists_and_restores_ewmas(tmp_path):
    """PR 19 leftover: learned EWMAs are durable. Every observation
    snapshots to disk; a fresh router on the same path boots with the
    tables (counted in `restores`) instead of cold priors."""
    path = str(tmp_path / "agg_router.json")
    r = CostRouter(persist_path=path)
    assert r.restores == 0                        # nothing to seed yet
    for _ in range(8):
        r.observe_device("terms", 50_000)
        r.observe_host("terms", 500_000, 100)
    snap = r.snapshot()

    r2 = CostRouter(persist_path=path)            # "restart"
    assert r2.restores == 2                       # one family, two tables
    assert r2.snapshot() == snap
    # the measured flip survives the restart: 100 docs would route host
    # on priors, but the restored model knows the device is faster here
    assert r2.decide("terms", 100, 1024) == "device"


def test_cost_router_restart_round_trip_through_node(tmp_path):
    """Node-level: train the shared router, restart the node on the
    same data path, and find the seeded families in
    `_nodes/stats indices.aggs router_restores`."""
    from elasticsearch_tpu.node import Node

    data = str(tmp_path / "data")
    n = Node(data)
    router = n._agg_cost_router()
    for _ in range(4):
        router.observe_device("terms", 50_000)
        router.observe_host("terms", 500_000, 100)
    snap = router.snapshot()
    assert n.local_node_stats()["indices"]["aggs"]["router_restores"] == 0
    n.close()

    n2 = Node(data)
    try:
        r2 = n2._agg_cost_router()
        assert r2.snapshot() == snap
        stats = n2.local_node_stats()["indices"]["aggs"]
        assert stats["router_restores"] == 2
    finally:
        n2.close()


def test_cost_router_engine_counts_and_parity(ctx):
    engine = AggEngine(ctx.mapper_service, cost_router=True)
    rows = _rows(ctx)
    spec = {"t": {"terms": {"field": "cat"}}}
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    # tiny corpus: the prior routes host — identical json either way,
    # and the decision is COUNTED with a reason
    assert got is not None
    assert _json(got[0]) == _json(host)
    assert engine.stats["router_host_routed"] >= 1
    ent = engine.stats["fallback_reasons"]["routed_host_cheaper"]
    assert ent["count"] >= 1 and ent["docs"] >= len(rows)


def test_fallback_reasons_carry_doc_totals(ctx, engine):
    rows = _rows(ctx)
    spec = {"t": {"terms": {"field": "tags"}}}  # multi-valued: host path
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    if got is not None:
        assert _json(got[0]) == _json(host)
    ent = engine.stats["fallback_reasons"]["multi_valued_field"]
    assert ent == {"count": 1, "docs": len(rows)}


def test_cardinality_off_grid_records_observed(ctx, engine, monkeypatch):
    """The ordinal-count fallback reports the cardinality that busted
    the ladder, so grid growth is driven by observed field shapes."""
    monkeypatch.setattr(aggs_ops, "AGG_B_LADDER", (8,))
    rows = _rows(ctx)
    spec = {"t": {"terms": {"field": "v"}}}
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None
    assert _json(got[0]) == _json(host)  # host fallback, identical json
    ent = engine.stats["fallback_reasons"]["cardinality_off_grid"]
    assert ent["observed_max"] > 8
    assert ent["docs"] == len(rows)


def test_warmup_ord_rungs_clamped(ctx, engine):
    """One pathological high-cardinality field must not AOT-warm the
    giant grid rungs: the ordinal warmup probe clamps at
    WARMUP_MAX_ORD_B."""
    col = engine.store.column(ctx.reader, "cat", want_ords=True)
    assert col.ord_keys
    col.ord_keys = [str(i) for i in range(40_000)]  # pretend: huge field
    entries = engine.store.warmup_entries(col)
    ord_rungs = [st["n_buckets"] for name, _spec, st in entries
                 if name == "aggs.ord_counts"]
    assert ord_rungs
    assert max(ord_rungs) <= aggs_ops.WARMUP_MAX_ORD_B
    # the rung-2 kernels ride the same warmup grid
    names = {name for name, _spec, _st in entries}
    assert "aggs.tree_counts" in names


# ---------------------------------------------------------------------------
# closed grid: strict zero-recompile second pass (single-device)
# ---------------------------------------------------------------------------


def test_strict_zero_recompile_second_pass_rung2(ctx, engine):
    rows = _rows(ctx)
    spec = {"cal": {"date_histogram": {"field": "ts",
                                       "calendar_interval": "month"}},
            "tree": {"terms": {"field": "cat"},
                     "aggs": {"by_sub": {"terms": {"field": "sub"},
                                         "aggs": {"s": {"stats": {
                                             "field": "v"}}}}}},
            "card": {"cardinality": {"field": "v"}}}
    engine.compute(ctx, rows, spec, partial=False)  # warm pass
    engine.compute(ctx, rows, spec, partial=True)   # warm the HLL boards
    before = dispatch.DISPATCH.compile_count()
    strict_before = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    try:
        got = engine.compute(ctx, rows, spec, partial=False)
        gp = engine.compute(ctx, rows, spec, partial=True)
    finally:
        dispatch.DISPATCH.strict = strict_before
    assert got is not None and gp is not None
    assert dispatch.DISPATCH.compile_count() == before


# ---------------------------------------------------------------------------
# SPMD mesh twins (the 8 virtual CPU devices conftest forces)
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
class TestMeshRung2:
    def _mk(self, n=900):
        e = Engine(tempfile.mkdtemp() + "/shard", MapperService(MAPPING))
        _index_docs(e, n=n)  # 900 live rows -> 1024 row bucket: ragged
        ctx = SearchContext(e.acquire_searcher(), e.mapper_service)
        return e, ctx

    MESH_SPECS = [
        {"d": {"date_histogram": {"field": "ts",
                                  "calendar_interval": "quarter"}}},
        {"d": {"date_histogram": {"field": "ts_dst",
                                  "calendar_interval": "day",
                                  "time_zone": "America/New_York"}}},
        {"t": {"terms": {"field": "cat"},
               "aggs": {"by_sub": {"terms": {"field": "sub"},
                                   "aggs": {"s": {"stats": {
                                       "field": "v"}}}}}}},
        {"c": {"cardinality": {"field": "v"}}},
        {"t": {"terms": {"field": "cat"},
               "aggs": {"cd": {"cardinality": {"field": "sub"}}}}},
    ]

    def test_mesh_parity_rung2(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = _rows(ctx)
            for spec in self.MESH_SPECS:
                host = compute_aggs(ctx, rows, spec)
                got = engine.compute(ctx, rows, spec, partial=False)
                assert got is not None
                assert _json(got[0]) == _json(host)
            assert engine.stats["mesh_dispatches"] > 0
        finally:
            e.close()

    def test_mesh_partial_hll_states_merge_like_host(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = ctx.all_rows()
            splits = [rows[:100], rows[100:600], rows[600:]]
            spec = {"t": {"terms": {"field": "cat"},
                          "aggs": {"cd": {"cardinality": {
                              "field": "v"}}}}}
            hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
            hm = hp[0]
            for p in hp[1:]:
                hm = merge_partial_aggs(hm, p, spec)
            dp = [engine.compute(ctx, r, spec, partial=True)[0]
                  for r in splits]
            dm = dp[0]
            for p in dp[1:]:
                dm = merge_partial_aggs(dm, p, spec)
            assert _json(finalize_aggs(dm, spec)) == \
                _json(finalize_aggs(hm, spec))
        finally:
            e.close()

    def test_mesh_strict_zero_recompile_second_pass(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = _rows(ctx)
            spec = {"cal": {"date_histogram": {
                        "field": "ts", "calendar_interval": "month"}},
                    "tree": {"terms": {"field": "cat"},
                             "aggs": {"by_sub": {"terms": {
                                 "field": "sub"}}}},
                    "card": {"cardinality": {"field": "v"}}}
            engine.compute(ctx, rows, spec, partial=False)  # warm
            before = dispatch.DISPATCH.compile_count()
            strict_before = dispatch.DISPATCH.strict
            dispatch.DISPATCH.strict = True
            try:
                got = engine.compute(ctx, rows, spec, partial=False)
            finally:
                dispatch.DISPATCH.strict = strict_before
            assert got is not None
            assert dispatch.DISPATCH.compile_count() == before
        finally:
            e.close()
