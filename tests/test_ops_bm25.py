"""Device-resident BM25 lexical engine (ops/bm25.py).

The engine's contract is strict: precomputed tile-padded impacts scored
through the batched device kernel (or its numpy host twin) must return
BYTE-IDENTICAL rows and scores to the live host path
(`search/queries.py` MatchQuery → bm25_scores → native.topk) — that
exactness is what lets the fused hybrid plan replace the two-phase
execution without a behavioural flag day.
"""

import tempfile

import numpy as np
import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.ops.bm25 import TILE, LexicalField, LexicalShard
from elasticsearch_tpu.search.queries import MatchQuery, SearchContext


@pytest.fixture(scope="module")
def corpus():
    ms = MapperService({"properties": {"body": {"type": "text"}}})
    eng = Engine(tempfile.mkdtemp(), ms)
    rng = np.random.default_rng(42)
    vocab = [f"tok{i}" for i in range(80)]
    for i in range(400):
        words = " ".join(rng.choice(vocab, size=rng.integers(2, 14)))
        eng.index(str(i), {"body": words})
    eng.refresh()
    return ms, eng, rng


def _reference(reader, ms, text, operator="or", window=100):
    """The live host path the engine must reproduce bit-for-bit."""
    ctx = SearchContext(reader, ms)
    ds = MatchQuery("body", text, operator=operator).execute(ctx) \
        .with_scores()
    idx = native.topk(ds.scores, min(window, len(ds.rows)))
    return ds.rows[idx], ds.scores[idx]


class TestParity:
    @pytest.mark.parametrize("route", ["host", "device"])
    def test_byte_identical_to_match_query(self, corpus, route):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lex = LexicalShard()
        for text in ("tok1 tok2", "tok5", "tok10 tok11 tok12 tok13"):
            ref_rows, ref_scores = _reference(reader, ms, text)
            (rows, scores), = lex.search_batch(
                reader, "body", [(text.split(), 1.0)], 100, route=route)
            assert np.array_equal(rows, ref_rows)
            # byte-identical, not approx: same impacts, same fold order
            assert scores.tobytes() == ref_scores.tobytes()

    @pytest.mark.parametrize("route", ["host", "device"])
    def test_operator_and_and_msm(self, corpus, route):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lex = LexicalShard()
        terms = ["tok1", "tok2", "tok3"]
        ref_rows, ref_scores = _reference(reader, ms, " ".join(terms),
                                          operator="and")
        (rows, scores), = lex.search_batch(
            reader, "body", [(terms, 1.0)], 100,
            required=[len(terms)], route=route)
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()

    def test_batch_matches_single_dispatch(self, corpus):
        """One batched device dispatch == N single dispatches: the scatter
        board is per-query, so coalescing must not change results."""
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lex = LexicalShard()
        queries = [(["tok1", "tok2"], 1.0), (["tok7"], 1.0),
                   (["tok3", "tok4", "tok5"], 1.0)]
        batched = lex.search_batch(reader, "body", queries, 50,
                                   route="device")
        for q, (rows, scores) in zip(queries, batched):
            (r1, s1), = lex.search_batch(reader, "body", [q], 50,
                                         route="device")
            assert np.array_equal(rows, r1)
            assert scores.tobytes() == s1.tobytes()

    def test_oov_terms_count_toward_required(self, corpus):
        """operator=and with an out-of-vocabulary term matches nothing —
        the host path's empty-clause semantics."""
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lex = LexicalShard()
        (rows, _), = lex.search_batch(
            reader, "body", [(["tok1", "zzz_never_indexed"], 1.0)], 100,
            required=[2], route="host")
        assert len(rows) == 0

    def test_window_cuts_ranked_prefix(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lex = LexicalShard()
        (full, fs), = lex.search_batch(reader, "body",
                                       [(["tok1", "tok2"], 1.0)], 1000)
        (cut, cs), = lex.search_batch(reader, "body",
                                      [(["tok1", "tok2"], 1.0)], 10)
        assert np.array_equal(cut, full[:10])
        assert cs.tobytes() == fs[:10].tobytes()


class TestRefresh:
    def test_append_only_refresh_and_delete_rebuild(self):
        ms = MapperService({"properties": {"body": {"type": "text"}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        for i in range(50):
            eng.index(str(i), {"body": f"alpha tok{i % 7}"})
        eng.refresh()
        lex = LexicalShard()
        reader = eng.acquire_searcher()
        lex.search_batch(reader, "body", [(["alpha"], 1.0)], 100)
        assert lex.stats["rebuilds"] == 1

        # same reader: no rebuild
        lex.search_batch(reader, "body", [(["alpha"], 1.0)], 100)
        assert lex.stats["rebuilds"] == 1

        # appended segment: rebuild picks up new docs + fresh global stats
        for i in range(50, 80):
            eng.index(str(i), {"body": f"alpha beta tok{i % 7}"})
        eng.refresh()
        reader2 = eng.acquire_searcher()
        ref_rows, ref_scores = _reference(reader2, ms, "alpha", window=100)
        (rows, scores), = lex.search_batch(reader2, "body",
                                           [(["alpha"], 1.0)], 100)
        assert lex.stats["rebuilds"] == 2
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()

        # delete: tombstoned doc disappears and scores re-match live stats
        eng.delete("3")
        eng.refresh()
        reader3 = eng.acquire_searcher()
        ref_rows, ref_scores = _reference(reader3, ms, "alpha", window=100)
        (rows, scores), = lex.search_batch(reader3, "body",
                                           [(["alpha"], 1.0)], 100)
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()
        assert not any(reader3.get_id(int(r)) == "3" for r in rows)


class TestLayout:
    def test_tiles_are_lane_padded(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lf = LexicalField("body")
        lf.sync(reader)
        assert lf.tile_slots.shape[1] == TILE
        assert lf.tile_impacts.shape == lf.tile_slots.shape
        # padding is -1 slots with zero impact
        pad = lf.tile_slots < 0
        assert np.all(lf.tile_impacts[pad] == 0.0)
        # every real slot is in range and the row map is ascending
        real = lf.tile_slots[~pad]
        assert real.min() >= 0 and real.max() < lf.n_slots
        assert np.all(np.diff(lf.row_map) > 0)

    def test_quantized_bf16_preserves_ranking(self, corpus):
        """bf16 impacts trade exactness for HBM; ranking of well-separated
        scores must hold (the parity contract applies to f32 only)."""
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        exact = LexicalShard(dtype="f32")
        quant = LexicalShard(dtype="bf16")
        (er, _), = exact.search_batch(reader, "body",
                                      [(["tok1", "tok2"], 1.0)], 10,
                                      route="device")
        (qr, _), = quant.search_batch(reader, "body",
                                      [(["tok1", "tok2"], 1.0)], 10,
                                      route="device")
        assert len(set(er.tolist()) & set(qr.tolist())) >= 8

    def test_int8_tile_scales_bound_error(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        lf = LexicalField("body", dtype="int8")
        lf.sync(reader)
        slots, impacts, scales = lf._device_arrays()
        deq = np.asarray(impacts, dtype=np.float32) \
            * np.asarray(scales)[:, None]
        err = np.abs(deq - lf.tile_impacts)
        # symmetric per-tile int8: error bounded by scale/2 per entry
        assert np.all(err <= np.asarray(scales)[:, None] * 0.5 + 1e-7)
