"""Language analysis + custom analyzers from index settings (reference:
modules/analysis-common, plugins/analysis-{icu,phonetic,kuromoji,nori,
smartcn,...}, AnalysisRegistry building per-index components)."""

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.index.analysis import DEFAULT_REGISTRY, AnalysisRegistry
from elasticsearch_tpu.index.analysis_lang import (
    cjk_tokenizer,
    metaphone,
    soundex,
)
from elasticsearch_tpu.node import Node


def test_language_analyzers_registered():
    for lang in ("french", "german", "spanish", "italian", "portuguese",
                 "dutch", "russian", "swedish", "norwegian", "danish",
                 "finnish", "cjk", "kuromoji", "nori", "smartcn",
                 "icu_analyzer"):
        assert DEFAULT_REGISTRY.get(lang) is not None


def test_french_stemming_and_elision():
    a = DEFAULT_REGISTRY.get("french")
    # stopwords removed, elision stripped, suffixes conflated
    assert a.terms("l'avion et les avions") == ["avion", "avion"]
    # same stem for inflections
    assert a.terms("nationale")[0] == a.terms("nationales")[0]


def test_german_stemming():
    a = DEFAULT_REGISTRY.get("german")
    assert a.terms("der Hund und die Hunde") == ["hund", "hund"]


def test_russian_analyzer():
    a = DEFAULT_REGISTRY.get("russian")
    t1 = a.terms("книга")
    t2 = a.terms("книги")
    assert t1 and t1 == t2  # inflections conflate


def test_cjk_bigrams():
    toks = [t.term for t in cjk_tokenizer("日本語テキスト")]
    assert "日本" in toks and "本語" in toks
    # mixed latin + cjk
    toks = [t.term for t in cjk_tokenizer("Hello 世界")]
    assert "hello" in toks and "世界" in toks
    # hangul
    toks = [t.term for t in cjk_tokenizer("한국어")]
    assert "한국" in toks and "국어" in toks


def test_icu_folding():
    a = DEFAULT_REGISTRY.get("icu_analyzer")
    assert a.terms("Ｈéllo ＷÖRLD") == ["hello", "world"]


def test_phonetic_encoders():
    assert soundex("robert") == soundex("rupert")
    assert soundex("smith") == soundex("smyth")
    assert metaphone("phone") == metaphone("fone")
    assert metaphone("night") != ""


def test_custom_analyzer_from_index_settings():
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.filter.my_syns.type": "synonym",
        "index.analysis.filter.my_syns.synonyms": ["car, automobile",
                                                   "tv => television"],
        "index.analysis.analyzer.my_an.type": "custom",
        "index.analysis.analyzer.my_an.tokenizer": "standard",
        "index.analysis.analyzer.my_an.filter": ["lowercase", "my_syns"],
    })
    a = reg.get("my_an")
    assert sorted(a.terms("Car")) == ["automobile", "car"]
    assert a.terms("TV") == ["television"]


def test_custom_edge_ngram_tokenizer():
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.tokenizer.auto.type": "edge_ngram",
        "index.analysis.tokenizer.auto.min_gram": 2,
        "index.analysis.tokenizer.auto.max_gram": 4,
        "index.analysis.analyzer.ac.type": "custom",
        "index.analysis.analyzer.ac.tokenizer": "auto",
        "index.analysis.analyzer.ac.filter": ["lowercase"],
    })
    assert reg.get("ac").terms("Quick") == ["qu", "qui", "quic"]


def test_custom_stop_filter_language_set():
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.filter.fr_stop.type": "stop",
        "index.analysis.filter.fr_stop.stopwords": "_french_",
        "index.analysis.analyzer.fr.type": "custom",
        "index.analysis.analyzer.fr.tokenizer": "standard",
        "index.analysis.analyzer.fr.filter": ["lowercase", "fr_stop"],
    })
    assert reg.get("fr").terms("le chat") == ["chat"]


def test_unknown_filter_rejected():
    with pytest.raises(IllegalArgumentError):
        AnalysisRegistry.from_index_settings({
            "index.analysis.analyzer.x.type": "custom",
            "index.analysis.analyzer.x.tokenizer": "standard",
            "index.analysis.analyzer.x.filter": ["definitely_not_a_filter"],
        })


def test_import_order_independent():
    """Importing analysis_lang before analysis must not crash (lazy
    DEFAULT_REGISTRY)."""
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-c",
         "import elasticsearch_tpu.index.analysis_lang; "
         "from elasticsearch_tpu.index.analysis import DEFAULT_REGISTRY; "
         "assert DEFAULT_REGISTRY.get('french')"],
        capture_output=True, cwd=".", env={"PYTHONPATH": ".",
                                           "PATH": "/usr/bin:/bin"})
    assert r.returncode == 0, r.stderr.decode()


def test_stopword_macros():
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.analyzer.s.type": "standard",
        "index.analysis.analyzer.s.stopwords": "_french_"})
    assert reg.get("s").terms("le chat et le chien") == ["chat", "chien"]
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.filter.ns.type": "stop",
        "index.analysis.filter.ns.stopwords": "_none_",
        "index.analysis.analyzer.a.type": "custom",
        "index.analysis.analyzer.a.tokenizer": "standard",
        "index.analysis.analyzer.a.filter": ["lowercase", "ns"]})
    assert reg.get("a").terms("to be or not") == ["to", "be", "or", "not"]
    with pytest.raises(IllegalArgumentError):
        AnalysisRegistry.from_index_settings({
            "index.analysis.filter.x.type": "stop",
            "index.analysis.filter.x.stopwords": "_klingon_",
            "index.analysis.analyzer.a.type": "custom",
            "index.analysis.analyzer.a.tokenizer": "standard",
            "index.analysis.analyzer.a.filter": ["x"]})
    with pytest.raises(IllegalArgumentError):
        AnalysisRegistry.from_index_settings({
            "index.analysis.filter.st.type": "stemmer",
            "index.analysis.filter.st.language": "klingon",
            "index.analysis.analyzer.a.type": "custom",
            "index.analysis.analyzer.a.tokenizer": "standard",
            "index.analysis.analyzer.a.filter": ["st"]})


def test_pattern_tokenizer_offsets():
    reg = AnalysisRegistry.from_index_settings({
        "index.analysis.tokenizer.p.type": "pattern",
        "index.analysis.tokenizer.p.pattern": ",",
        "index.analysis.analyzer.pa.type": "custom",
        "index.analysis.analyzer.pa.tokenizer": "p"})
    toks = reg.get("pa").analyze("foo,bar,baz")
    assert [(t.term, t.start_offset, t.end_offset) for t in toks] == \
        [("foo", 0, 3), ("bar", 4, 7), ("baz", 8, 11)]


def test_end_to_end_custom_analyzer_search(tmp_path):
    """Index created with a custom analyzer; text field uses it; search
    matches through synonyms."""
    node = Node(str(tmp_path / "d"))
    try:
        node.create_index_with_templates("products", settings={
            "index.analysis.filter.syn.type": "synonym",
            "index.analysis.filter.syn.synonyms": ["laptop, notebook"],
            "index.analysis.analyzer.product_an.type": "custom",
            "index.analysis.analyzer.product_an.tokenizer": "standard",
            "index.analysis.analyzer.product_an.filter": ["lowercase",
                                                          "syn"],
        }, mappings={"properties": {
            "name": {"type": "text", "analyzer": "product_an"}}})
        node.index_doc("products", "1", {"name": "Gaming Laptop"},
                       refresh="true")
        resp = node.search("products", {"query": {"match": {"name":
                                                            "notebook"}}})
        assert resp["hits"]["total"]["value"] == 1
        # _analyze with index-scoped analyzer
        out = node.analyze({"analyzer": "product_an",
                            "text": "notebook"}, index="products")
        assert sorted(t["token"] for t in out["tokens"]) == ["laptop",
                                                             "notebook"]
    finally:
        node.close()


def test_phonetic_search_end_to_end(tmp_path):
    node = Node(str(tmp_path / "d"))
    try:
        node.create_index_with_templates("people", settings={
            "index.analysis.filter.ph.type": "phonetic",
            "index.analysis.filter.ph.encoder": "soundex",
            "index.analysis.analyzer.name_ph.type": "custom",
            "index.analysis.analyzer.name_ph.tokenizer": "standard",
            "index.analysis.analyzer.name_ph.filter": ["lowercase", "ph"],
        }, mappings={"properties": {
            "name": {"type": "text", "analyzer": "name_ph"}}})
        node.index_doc("people", "1", {"name": "Robert"}, refresh="true")
        resp = node.search("people", {"query": {"match": {"name":
                                                          "Rupert"}}})
        assert resp["hits"]["total"]["value"] == 1  # phonetic match
    finally:
        node.close()
