"""Watcher, transform, and rollup.

Reference behaviors: x-pack/plugin/watcher (trigger/input/condition/actions,
ack + throttle), x-pack/plugin/transform (pivot + latest into dest index),
x-pack/plugin/rollup (date-histogram downsampling).
"""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


# ------------------------------------------------------------------ watcher

def _error_watch():
    return {
        "trigger": {"schedule": {"interval": "10s"}},
        "input": {"search": {"request": {
            "indices": ["logs"],
            "body": {"query": {"term": {"level": "error"}}}}}},
        "condition": {"compare": {"ctx.payload.hits.total.value": {"gt": 0}}},
        "actions": {"store": {"index": {"index": "alerts"}}},
    }


def test_watch_crud(client):
    st, body = client.req("PUT", "/_watcher/watch/w1", _error_watch())
    assert st == 200 and body["created"]
    st, body = client.req("GET", "/_watcher/watch/w1")
    assert body["found"] and "trigger" in body["watch"]
    st, _ = client.req("DELETE", "/_watcher/watch/w1")
    assert st == 200
    st, _ = client.req("GET", "/_watcher/watch/w1")
    assert st == 404


def test_watch_condition_and_index_action(client, node):
    client.req("PUT", "/logs/_doc/1", {"level": "info", "msg": "ok"})
    client.req("POST", "/logs/_refresh")
    client.req("PUT", "/_watcher/watch/errs", _error_watch())
    # no errors yet → condition false
    record = node.watcher.execute("errs")
    assert record["condition_met"] is False
    # add an error → condition true, index action fires
    client.req("PUT", "/logs/_doc/2", {"level": "error", "msg": "boom"})
    client.req("POST", "/logs/_refresh")
    record = node.watcher.execute("errs")
    assert record["condition_met"] is True
    assert record["actions"][0]["status"] == "success"
    client.req("POST", "/alerts/_refresh")
    st, body = client.req("GET", "/_alerts/_count") if False else client.req("GET", "/alerts/_count")
    assert body["count"] == 1


def test_watch_interval_scheduling(client, node):
    client.req("PUT", "/_watcher/watch/tick", {
        "trigger": {"schedule": {"interval": "10s"}},
        "input": {"simple": {"n": 1}},
        "condition": {"always": {}},
        "actions": {"log": {"logging": {"text": "fired"}}}})
    t0 = 1_000_000_000_000
    assert len(node.watcher.run_once(now_ms=t0)) == 1
    # 5s later: not due
    assert len(node.watcher.run_once(now_ms=t0 + 5_000)) == 0
    # 11s later: due again
    assert len(node.watcher.run_once(now_ms=t0 + 11_000)) == 1


def test_watch_throttle_and_ack(client, node):
    client.req("PUT", "/_watcher/watch/tw", {
        "trigger": {"schedule": {"interval": "1s"}},
        "input": {"simple": {}},
        "condition": {"always": {}},
        "throttle_period": "60s",
        "actions": {"log": {"logging": {"text": "x"}}}})
    t0 = 1_000_000_000_000
    r1 = node.watcher.execute("tw", now_ms=t0)
    assert r1["actions"][0]["status"] == "success"
    r2 = node.watcher.execute("tw", now_ms=t0 + 10_000)
    assert r2["actions"][0]["status"] == "throttled"
    # ack suppresses even past throttle
    client.req("POST", "/_watcher/watch/tw/_ack")
    r3 = node.watcher.execute("tw", now_ms=t0 + 120_000)
    assert r3["actions"][0]["status"] == "acked"


def test_watch_mustache_in_action(client, node):
    client.req("PUT", "/_watcher/watch/tpl", {
        "trigger": {"schedule": {"interval": "1s"}},
        "input": {"simple": {"who": "world"}},
        "condition": {"always": {}},
        "actions": {"log": {"logging": {"text": "hello {{ctx.payload.who}}"}}}})
    record = node.watcher.execute("tpl")
    assert record["actions"][0]["logging"]["logged_text"] == "hello world"


def test_watch_script_condition(client, node):
    client.req("PUT", "/_watcher/watch/sc", {
        "trigger": {"schedule": {"interval": "1s"}},
        "input": {"simple": {"value": 42}},
        "condition": {"script": {"source": "ctx.payload.value > params.lim",
                                 "params": {"lim": 40}}},
        "actions": {"log": {"logging": {"text": "big"}}}})
    assert node.watcher.execute("sc")["condition_met"] is True


def test_watch_activate_deactivate(client, node):
    client.req("PUT", "/_watcher/watch/onoff", {
        "trigger": {"schedule": {"interval": "1s"}},
        "input": {"simple": {}}, "condition": {"always": {}},
        "actions": {"log": {"logging": {"text": "x"}}}})
    client.req("POST", "/_watcher/watch/onoff/_deactivate")
    assert node.watcher.run_once(now_ms=123456789) == []
    client.req("POST", "/_watcher/watch/onoff/_activate")
    assert len(node.watcher.run_once(now_ms=123456789)) == 1


# ---------------------------------------------------------------- transform

def _seed_sales(client):
    sales = [("a", "2024-01-01T10:00:00Z", 10), ("a", "2024-01-01T11:00:00Z", 20),
             ("b", "2024-01-01T10:30:00Z", 5), ("b", "2024-01-02T09:00:00Z", 7),
             ("a", "2024-01-02T12:00:00Z", 30)]
    for i, (cust, ts, amt) in enumerate(sales):
        client.req("PUT", f"/sales/_doc/{i}",
                   {"customer": cust, "ts": ts, "amount": amt})
    client.req("POST", "/sales/_refresh")


def test_transform_pivot(client, node):
    _seed_sales(client)
    st, _ = client.req("PUT", "/_transform/by-customer", {
        "source": {"index": "sales"},
        "dest": {"index": "customer-totals"},
        "pivot": {
            "group_by": {"customer": {"terms": {"field": "customer"}}},
            "aggregations": {"total": {"sum": {"field": "amount"}},
                             "avg_amount": {"avg": {"field": "amount"}}}}})
    assert st == 200
    client.req("POST", "/_transform/by-customer/_start")
    st, body = client.req("GET", "/customer-totals/_search",
                          {"query": {"term": {"customer": "a"}}})
    hit = body["hits"]["hits"][0]["_source"]
    assert hit["total"] == 60.0
    assert hit["avg_amount"] == 20.0
    st, body = client.req("GET", "/_transform/by-customer/_stats")
    assert body["transforms"][0]["stats"]["documents_indexed"] == 2


def test_transform_preview(client):
    _seed_sales(client)
    st, body = client.req("POST", "/_transform/_preview", {
        "source": {"index": "sales"}, "dest": {"index": "x"},
        "pivot": {"group_by": {"customer": {"terms": {"field": "customer"}}},
                  "aggregations": {"n": {"value_count": {"field": "amount"}}}}})
    assert st == 200
    assert len(body["preview"]) == 2


def test_transform_latest(client, node):
    _seed_sales(client)
    client.req("PUT", "/_transform/latest-sale", {
        "source": {"index": "sales"},
        "dest": {"index": "latest-sales"},
        "latest": {"unique_key": ["customer"], "sort": "ts"}})
    client.req("POST", "/_transform/latest-sale/_start")
    st, body = client.req("GET", "/latest-sales/_search",
                          {"query": {"term": {"customer": "a"}}})
    assert body["hits"]["hits"][0]["_source"]["amount"] == 30


# ------------------------------------------------------------------- rollup

def test_rollup_job(client, node):
    _seed_sales(client)
    st, _ = client.req("PUT", "/_rollup/job/daily", {
        "index_pattern": "sales",
        "rollup_index": "sales-rollup",
        "cron": "0 0 * * * ?",
        "groups": {
            "date_histogram": {"field": "ts", "calendar_interval": "1d"},
            "terms": {"fields": ["customer"]}},
        "metrics": [{"field": "amount", "metrics": ["sum", "max"]}]})
    assert st == 200
    st, _ = client.req("POST", "/_rollup/job/daily/_start")
    client.req("POST", "/sales-rollup/_refresh")
    st, body = client.req("POST", "/sales-rollup/_search",
                          {"size": 10, "query": {"match_all": {}}})
    docs = [h["_source"] for h in body["hits"]["hits"]]
    assert len(docs) == 4   # 2 days x 2 customers (a has both days, b both)
    day1_a = [d for d in docs
              if d["customer.terms"] == "a" and "amount.sum" in d]
    assert any(d["amount.sum"] == 30.0 for d in day1_a)
    st, body = client.req("GET", "/_rollup/data/sales")
    assert "sales" in body
    assert body["sales"]["rollup_jobs"][0]["job_id"] == "daily"


def test_transform_continuous_checkpoints(tmp_path):
    """Continuous (sync'd) transforms checkpoint on every tick: new source
    docs flow into dest and the checkpoint counter advances
    (TransformTask + TransformCheckpointService analog)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from elasticsearch_tpu.node import Node

    node = Node(str(tmp_path))
    node.create_index_with_templates("src", mappings={"properties": {
        "user": {"type": "keyword"}, "n": {"type": "long"},
        "ts": {"type": "date"}}})
    node.index_doc("src", "1", {"user": "a", "n": 1,
                                "ts": "2020-01-01T00:00:00Z"})
    node.indices.get("src").refresh()
    node.transform.put("t1", {
        "source": {"index": "src"},
        "dest": {"index": "dst"},
        "sync": {"time": {"field": "ts"}},
        "pivot": {"group_by": {"user": {"terms": {"field": "user"}}},
                  "aggregations": {"total": {"sum": {"field": "n"}}}}})
    node.transform.start("t1")
    node.transform.run_once()
    cp1 = node.transform.state["t1"]["checkpoint"]
    assert cp1 >= 1
    r = node.search("dst", {"query": {"term": {"user": "a"}}})
    assert r["hits"]["hits"][0]["_source"]["total"] == 1.0

    # new source data: the next tick advances the checkpoint and upserts
    node.index_doc("src", "2", {"user": "a", "n": 4,
                                "ts": "2020-01-01T01:00:00Z"})
    node.indices.get("src").refresh()
    node.transform.run_once()
    assert node.transform.state["t1"]["checkpoint"] > cp1
    r = node.search("dst", {"query": {"term": {"user": "a"}}})
    assert r["hits"]["hits"][0]["_source"]["total"] == 5.0
    stats = node.transform.stats("t1")
    assert stats["transforms"][0]["checkpointing"]["last"]["checkpoint"] >= 2
    node.close()


def test_transform_repeated_failures_flip_to_failed(tmp_path):
    """A permanently failing continuous transform records its failures in
    state/_stats and flips to `failed` after MAX_CONSECUTIVE_FAILURES
    instead of silently retrying forever (TransformTask.fail analog)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.xpack import transform as transform_mod

    node = Node(str(tmp_path))
    node.create_index_with_templates("src", mappings={"properties": {
        "user": {"type": "keyword"}, "n": {"type": "long"},
        "ts": {"type": "date"}}})
    node.index_doc("src", "1", {"user": "a", "n": 1,
                                "ts": "2020-01-01T00:00:00Z"})
    node.indices.get("src").refresh()
    node.transform.put("t1", {
        "source": {"index": "src"},
        "dest": {"index": "dst"},
        "sync": {"time": {"field": "ts"}},
        "pivot": {"group_by": {"user": {"terms": {"field": "user"}}},
                  "aggregations": {"total": {"sum": {"field": "n"}}}}})
    node.transform.start("t1")

    # break the trigger permanently; each tick must see "new" source data
    def boom(tid):
        raise RuntimeError("dest exploded")

    node.transform.trigger = boom
    st = node.transform.state["t1"]
    for i in range(transform_mod.MAX_CONSECUTIVE_FAILURES):
        st["last_source_fp"] = f"force-dirty-{i}"
        node.transform.run_once()
    assert st["state"] == "failed"
    assert "dest exploded" in st["reason"]
    stats = node.transform.stats("t1")["transforms"][0]
    assert stats["state"] == "failed"
    assert "dest exploded" in stats["reason"]
    assert stats["stats"]["index_failures"] \
        == transform_mod.MAX_CONSECUTIVE_FAILURES
    # a failed task no longer ticks
    before = st["failure_count"]
    st["last_source_fp"] = "force-dirty-again"
    node.transform.run_once()
    assert st["failure_count"] == before
    node.close()


def test_rollup_repeated_failures_flip_to_failed(tmp_path):
    """Rollup jobs share the transform failure contract: repeated tick
    failures surface in state and flip job_state to failed."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.xpack import transform as transform_mod

    node = Node(str(tmp_path))
    node.create_index_with_templates("sales", mappings={"properties": {
        "ts": {"type": "date"}, "amount": {"type": "double"}}})
    node.index_doc("sales", "1", {"ts": "2020-01-01T00:00:00Z",
                                  "amount": 10.0})
    node.indices.get("sales").refresh()
    node.rollup.put_job("daily", {
        "index_pattern": "sales",
        "rollup_index": "sales-rollup",
        "cron": "0 0 * * * ?",
        "groups": {"date_histogram": {"field": "ts",
                                      "calendar_interval": "1d"}},
        "metrics": [{"field": "amount", "metrics": ["sum"]}]})
    node.rollup.start_job("daily")

    def boom(jid):
        raise RuntimeError("rollup dest exploded")

    node.rollup.trigger = boom
    st = node.rollup.state["daily"]
    for i in range(transform_mod.MAX_CONSECUTIVE_FAILURES):
        st["last_source_fp"] = f"force-dirty-{i}"
        node.rollup.run_once()
    assert st["job_state"] == "failed"
    assert "rollup dest exploded" in st["reason"]
    # a failed job no longer ticks
    before = st["failure_count"]
    st["last_source_fp"] = "force-dirty-again"
    node.rollup.run_once()
    assert st["failure_count"] == before
    node.close()
