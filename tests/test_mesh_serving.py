"""Mesh-sharded serving (parallel/policy.py + sharded_knn/sharded_ivf).

Two contracts gate SPMD promotion from bench demo to default serving
mode, both pinned here on the 8 virtual CPU devices conftest forces
(same XLA partitioner as a real mesh — program structure, not ICI):

1. PARITY — sharded execution is result-identical to single-device:
   exact kNN and IVF top-k byte-parity at the kernel layer, and full
   `rank.rrf` / knn response parity through the REST controller.

2. CLOSED GRID — steady-state sharded serving compiles nothing: the
   second pass over the sharded grid runs under strict dispatch with a
   zero `compiles` delta for the kNN, IVF, and hybrid legs.
"""

import json
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.parallel import mesh as mesh_lib
from elasticsearch_tpu.parallel.sharded_knn import (
    ShardedFieldState,
    distributed_knn_search,
)

pytestmark = pytest.mark.multidevice


def _single_device_knn(vectors, queries, k, metric="cosine",
                       precision="f32", filter_mask=None):
    corpus = knn_ops.build_corpus(vectors, metric=metric, dtype="f32")
    s, i = knn_ops.knn_search(jnp.asarray(queries), corpus, k,
                              metric=metric, precision=precision,
                              filter_mask=filter_mask)
    return np.asarray(s), np.asarray(i)


def _mesh_knn(state, queries, k, metric="cosine", precision="f32",
              mask=None):
    q = jax.device_put(jnp.asarray(queries), state.query_sharding())
    if mask is not None:
        mask = jax.device_put(jnp.asarray(mask),
                              state.mask_sharding(mask.ndim))
    s, g = distributed_knn_search(q, state.corpus, k, state.mesh,
                                  metric=metric, filter_mask=mask,
                                  precision=precision)
    return np.asarray(s), state.map_ids(np.asarray(g))


# ------------------------------------------------------------ kernels


class TestShardedKnnParity:
    def test_byte_parity_vs_single_device(self, mesh_serving):
        rng = np.random.default_rng(0)
        vectors = rng.standard_normal((1000, 64)).astype(np.float32)
        queries = rng.standard_normal((8, 64)).astype(np.float32)
        state = ShardedFieldState(vectors, mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        s_mesh, rows_mesh = _mesh_knn(state, queries, 10)
        s_one, rows_one = _single_device_knn(vectors, queries, 10)
        assert np.array_equal(rows_mesh, rows_one)
        # byte-identical, not approx: same matmul precision, the sharded
        # merge only reorders candidates that were scored identically
        assert s_mesh.tobytes() == s_one.tobytes()

    def test_ragged_shard_padding_never_leaks(self, mesh_serving):
        """The padded-row escape (ISSUE 5): 37 rows over 8 shards leaves
        every shard ragged; k=16 exceeds each shard's num_valid, so
        un-masked padding rows would enter the merge as aliased ids."""
        rng = np.random.default_rng(1)
        vectors = rng.standard_normal((37, 16)).astype(np.float32)
        queries = rng.standard_normal((8, 16)).astype(np.float32)
        state = ShardedFieldState(vectors, mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        s_mesh, rows_mesh = _mesh_knn(state, queries, 16)
        # padding must surface as (-inf, -1), never as an aliased row
        valid = s_mesh > -np.inf
        assert (rows_mesh[valid] >= 0).all()
        assert (rows_mesh[valid] < 37).all()
        assert (rows_mesh[~valid] == -1).all()
        s_one, rows_one = _single_device_knn(vectors, queries, 16)
        assert np.array_equal(rows_mesh[valid],
                              rows_one[np.asarray(s_one) > -1e37])
        assert s_mesh[valid].tobytes() == \
            s_one[np.asarray(s_one) > -1e37].tobytes()

    def test_per_query_filter_parity(self, mesh_serving):
        rng = np.random.default_rng(2)
        n = 600
        vectors = rng.standard_normal((n, 32)).astype(np.float32)
        queries = rng.standard_normal((8, 32)).astype(np.float32)
        state = ShardedFieldState(vectors, mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        allowed = rng.random((8, n)) < 0.3
        mask = np.stack([state.filter_mask(a) for a in allowed])
        s_mesh, rows_mesh = _mesh_knn(state, queries, 10, mask=mask)
        corpus = knn_ops.build_corpus(vectors, metric="cosine",
                                      dtype="f32")
        pad_n = corpus.matrix.shape[0]
        allowed_pad = np.zeros((8, pad_n), dtype=bool)
        allowed_pad[:, :n] = allowed
        s_one, rows_one = _single_device_knn(
            vectors, queries, 10, filter_mask=jnp.asarray(allowed_pad))
        v = s_one > -1e37
        assert np.array_equal(rows_mesh[v], rows_one[v])
        assert s_mesh[v].tobytes() == s_one[v].tobytes()
        # filtered-out slots surface as (-inf, -1) on the mesh
        assert (rows_mesh[~v] == -1).all()

    def test_incremental_append_parity(self, mesh_serving):
        """Refresh appends land in per-shard headroom via `mesh.append`
        (delta-only upload) and must serve identically to a corpus built
        whole."""
        rng = np.random.default_rng(3)
        vectors = rng.standard_normal((2000, 32)).astype(np.float32)
        queries = rng.standard_normal((8, 32)).astype(np.float32)
        state = ShardedFieldState(vectors[:1500],
                                  mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        assert state.can_append(500)
        old = state
        state = state.append(vectors[1500:])
        assert state.n_rows == 2000
        assert int(state.shard_counts.sum()) == 2000
        # copy-on-write: the pre-append snapshot an in-flight search
        # captured must be untouched and still serve from live buffers
        assert old.n_rows == 1500
        assert int(old.shard_counts.sum()) == 1500
        s_old, rows_old = _mesh_knn(old, queries, 10)
        s_ref, rows_ref = _single_device_knn(vectors[:1500], queries, 10)
        assert np.array_equal(rows_old, rows_ref)
        assert s_old.tobytes() == s_ref.tobytes()
        s_mesh, rows_mesh = _mesh_knn(state, queries, 10)
        s_one, rows_one = _single_device_knn(vectors, queries, 10)
        # appended rows land in whichever shard had headroom, so the
        # merge may order equal-score candidates differently — compare
        # as ranked sets
        assert np.array_equal(np.sort(rows_mesh, axis=1),
                              np.sort(rows_one, axis=1))
        assert np.sort(s_mesh, axis=1).tobytes() == \
            np.sort(s_one, axis=1).tobytes()

    def test_append_beyond_headroom_raises(self, mesh_serving):
        rng = np.random.default_rng(4)
        vectors = rng.standard_normal((256, 8)).astype(np.float32)
        state = ShardedFieldState(vectors, mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        too_many = state.headroom() + 1
        assert not state.can_append(too_many)
        with pytest.raises(ValueError, match="headroom"):
            state.append(rng.standard_normal((too_many, 8))
                         .astype(np.float32))

    def test_warmup_precompiles_sharded_grid(self, mesh_serving):
        """`warmup_entries` AOT specs (shape + NamedSharding) must key to
        the SAME executables live sharded traffic dispatches."""
        rng = np.random.default_rng(5)
        vectors = rng.standard_normal((512, 16)).astype(np.float32)
        state = ShardedFieldState(vectors, mesh_serving.serving_mesh(),
                                  "cosine", "f32")
        dispatch.DISPATCH.warmup(state.warmup_entries(16),
                                 background=False)
        before = dispatch.stats(per_bucket=False)
        queries = rng.standard_normal((8, 16)).astype(np.float32)
        _mesh_knn(state, queries, 10, precision="bf16")
        after = dispatch.stats(per_bucket=False)
        assert after["compiles"] == before["compiles"]
        assert after["hits"] > before["hits"]


class TestShardedIvfParity:
    def test_byte_parity_vs_single_device(self, mesh_serving):
        from elasticsearch_tpu.ann.ivf_index import build_ivf_index
        from elasticsearch_tpu.ann.router import IVFRouter

        rng = np.random.default_rng(6)
        vectors = rng.standard_normal((2000, 32)).astype(np.float32)
        queries = rng.standard_normal((8, 32)).astype(np.float32)
        index = build_ivf_index(vectors, metric="cosine", nlist=16,
                                dtype="f32")
        router = IVFRouter(index, nprobe=4)
        s_one, rows_one, ph_one = router.search(queries, 10, nprobe=4)
        s_mesh, rows_mesh, ph_mesh = router.search(
            queries, 10, nprobe=4, mesh=mesh_serving.serving_mesh())
        assert ph_mesh["engine"] == "tpu_ivf_mesh"
        assert ph_mesh["mesh_shards"] == 8
        assert np.array_equal(rows_mesh, rows_one)
        assert s_mesh.tobytes() == s_one.tobytes()

    def test_quantized_int8_parity(self, mesh_serving):
        from elasticsearch_tpu.ann.ivf_index import build_ivf_index
        from elasticsearch_tpu.ann.router import IVFRouter

        rng = np.random.default_rng(7)
        vectors = rng.standard_normal((1500, 16)).astype(np.float32)
        queries = rng.standard_normal((8, 16)).astype(np.float32)
        index = build_ivf_index(vectors, metric="cosine", nlist=16,
                                dtype="int8")
        router = IVFRouter(index, nprobe=4)
        s_one, rows_one, _ = router.search(queries, 10, nprobe=4)
        s_mesh, rows_mesh, _ = router.search(
            queries, 10, nprobe=4, mesh=mesh_serving.serving_mesh())
        assert np.array_equal(rows_mesh, rows_one)
        assert s_mesh.tobytes() == s_one.tobytes()


class TestShardedBm25Int8:
    def test_int8_impacts_mesh_parity(self, mesh_serving):
        """int8 tile scales are rank-1 [T]: the sharded kernel must
        accept them (regression: a rank-2 in_spec made every mesh-routed
        BM25 dispatch on an int8-impact index raise in shard_map) and
        score byte-identically to the single-device int8 board."""
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.index.mapping import MapperService
        from elasticsearch_tpu.ops.bm25 import LexicalShard

        ms = MapperService({"properties": {"body": {"type": "text"}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        rng = np.random.default_rng(13)
        vocab = [f"tok{i}" for i in range(40)]
        for i in range(300):
            words = " ".join(rng.choice(vocab, size=rng.integers(2, 10)))
            eng.index(str(i), {"body": words})
        eng.refresh()
        reader = eng.acquire_searcher()
        lex = LexicalShard(dtype="int8")
        queries = [(["tok1", "tok2"], 1.0), (["tok5"], 2.0),
                   (["tok7", "tok8", "tok9"], 1.0)]

        mesh_res = lex.search_batch(reader, "body", queries, 10,
                                    route="device")
        assert mesh_serving.stats()["router"]["mesh"] >= 1, \
            "int8 lexical dispatch did not route to the mesh"
        mesh_serving.configure(enabled=False)
        one_res = lex.search_batch(reader, "body", queries, 10,
                                   route="device")
        for (m_rows, m_scores), (o_rows, o_scores) in zip(mesh_res,
                                                          one_res):
            assert np.array_equal(m_rows, o_rows)
            assert m_scores.tobytes() == o_scores.tobytes()


# ----------------------------------------------------- store + REST


def _make_node(tmp, settings=None, n=900, dims=16, seed=11):
    from elasticsearch_tpu.node import Node

    rng = np.random.default_rng(seed)
    node = Node(tmp)
    node.create_index_with_templates("m", settings=settings or {},
                                     mappings={"properties": {
                                         "body": {"type": "text"},
                                         "tag": {"type": "keyword"},
                                         "v": {"type": "dense_vector",
                                               "dims": dims}}})
    ops = []
    for i in range(n):
        ops.append({"index": {"_index": "m", "_id": str(i)}})
        ops.append({"body": " ".join(rng.choice(list("abcdefgh"), 5)),
                    "tag": "even" if i % 2 == 0 else "odd",
                    "v": rng.standard_normal(dims).tolist()})
    node.bulk(ops)
    node.indices.get("m").refresh()
    return node, rng


def _strip_took(resp):
    resp = dict(resp)
    resp.pop("took", None)
    return json.dumps(resp, sort_keys=True)


class TestRestParity:
    def test_knn_and_rrf_response_parity_and_strict_second_pass(
            self, mesh_serving, monkeypatch):
        """One node, three serving legs (exact kNN, IVF via a second
        index, fused rank.rrf), each compared mesh-vs-single-device
        through the REST-facing search entry, then re-run under strict
        dispatch asserting the sharded grid is closed (zero compiles).

        The host int8 latency mirror is pinned OFF: the mesh replaces the
        DEVICE path, so that's the parity oracle (host-vs-device parity
        has its own suite in test_serving.py)."""
        from elasticsearch_tpu.serving.batcher import CostModel
        monkeypatch.setattr(CostModel, "prefer_host",
                            staticmethod(lambda *a, **kw: False))
        node, rng = _make_node(tempfile.mkdtemp())
        try:
            qv = rng.standard_normal(16).tolist()
            knn_body = {"knn": {"field": "v", "query_vector": qv,
                                "k": 10, "num_candidates": 50},
                        "size": 10}
            rrf_body = {
                "rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 20}},
                "query": {"match": {"body": "a b"}},
                "knn": {"field": "v", "query_vector": qv, "k": 10,
                        "num_candidates": 50},
                "size": 10}

            mesh_resp_knn = node.search("m", dict(knn_body))
            mesh_resp_rrf = node.search("m", json.loads(
                json.dumps(rrf_body)))
            stats = mesh_serving.stats()
            assert stats["available"] and stats["num_shards"] == 8
            assert stats["router"]["mesh"] >= 1
            assert "knn" in stats["legs"]
            knn_stats = node.indices.get("m").shards[0] \
                .vector_store.knn_stats
            assert knn_stats["mesh_searches"] >= 1

            # the same requests with the mesh router OFF: byte-identical
            # responses prove sharded execution changed nothing
            mesh_serving.configure(enabled=False)
            one_resp_knn = node.search("m", dict(knn_body))
            one_resp_rrf = node.search("m", json.loads(
                json.dumps(rrf_body)))
            assert _strip_took(mesh_resp_knn) == _strip_took(one_resp_knn)
            assert _strip_took(mesh_resp_rrf) == _strip_took(one_resp_rrf)

            # strict second pass: mesh back on, identical requests must
            # reuse every sharded executable (closed-grid acceptance)
            mesh_serving.configure(enabled=True, num_shards=8,
                                   min_rows=1)
            node.search("m", dict(knn_body))  # re-warm post-toggle
            node.search("m", json.loads(json.dumps(rrf_body)))
            before = dispatch.stats(per_bucket=False)
            old_strict = dispatch.DISPATCH.strict
            dispatch.DISPATCH.strict = True
            try:
                again_knn = node.search("m", dict(knn_body))
                again_rrf = node.search("m", json.loads(
                    json.dumps(rrf_body)))
            finally:
                dispatch.DISPATCH.strict = old_strict
            after = dispatch.stats(per_bucket=False)
            assert after["compiles"] == before["compiles"]
            assert after["out_of_grid_compiles"] == \
                before["out_of_grid_compiles"]
            assert _strip_took(again_knn) == _strip_took(mesh_resp_knn)
            assert _strip_took(again_rrf) == _strip_took(mesh_resp_rrf)
        finally:
            node.close()

    def test_ivf_engine_rides_mesh_through_store(self, mesh_serving):
        node, rng = _make_node(
            tempfile.mkdtemp(),
            settings={"index.knn.engine": "tpu_ivf",
                      "index.knn.nlist": 16, "index.knn.nprobe": 4},
            n=2000, seed=12)
        try:
            qv = rng.standard_normal(16).tolist()
            body = {"knn": {"field": "v", "query_vector": qv, "k": 10,
                            "num_candidates": 64}, "size": 10}
            mesh_resp = node.search("m", dict(body))
            store = node.indices.get("m").shards[0].vector_store
            assert store.last_knn_phases["engine"] == "tpu_ivf_mesh"
            assert store.knn_stats["mesh_searches"] >= 1
            mesh_serving.configure(enabled=False)
            one_resp = node.search("m", dict(body))
            assert store.last_knn_phases["engine"] == "tpu_ivf"
            assert _strip_took(mesh_resp) == _strip_took(one_resp)
        finally:
            node.close()

    def test_profile_and_nodes_stats_mesh_sections(self, mesh_serving):
        node, rng = _make_node(tempfile.mkdtemp(), seed=13)
        try:
            qv = rng.standard_normal(16).tolist()
            resp = node.search("m", {
                "knn": {"field": "v", "query_vector": qv, "k": 5,
                        "num_candidates": 20},
                "size": 5, "profile": True})
            shard_prof = resp["profile"]["shards"][0]
            assert shard_prof["mesh"]["shards"] == 8
            assert shard_prof["mesh"]["collective_bytes"] > 0
            assert shard_prof["mesh"]["breakdown"]["local_nanos"] > 0

            resp = node.search("m", {
                "rank": {"rrf": {"rank_window_size": 10}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v", "query_vector": qv, "k": 5,
                        "num_candidates": 20},
                "size": 5, "profile": True})
            hyb = resp["profile"]["hybrid"]
            assert hyb["mesh"]["shards"] == 8
            assert hyb["mesh"]["router"]["mesh"] >= 1
            assert "knn" in hyb["mesh"]["legs"]

            section = node._mesh_stats_section()
            assert section["available"] is True
            assert section["num_shards"] == 8
            assert section["router"]["mesh"] >= 2
            for leg, entry in section["legs"].items():
                assert entry["dispatches"] >= 1
                assert entry["collective_bytes"] > 0
        finally:
            node.close()

    def test_small_corpus_stays_single_device(self, mesh_serving):
        """The cost router's row floor: corpora under min_rows never pay
        the second resident copy or the all-gather merge."""
        mesh_serving.configure(enabled=True, num_shards=8,
                               min_rows=100_000)
        node, rng = _make_node(tempfile.mkdtemp(), n=200, seed=14)
        try:
            qv = rng.standard_normal(16).tolist()
            node.search("m", {"knn": {"field": "v", "query_vector": qv,
                                      "k": 5, "num_candidates": 20},
                              "size": 5})
            store = node.indices.get("m").shards[0].vector_store
            assert store.field("v").mesh_state is None
            assert store.knn_stats["mesh_searches"] == 0
            stats = mesh_serving.stats()
            assert stats["router"]["single_device"] >= 1
            reasons = stats["router"]["reasons"]
            assert reasons.get("corpus_below_min_rows", 0) \
                + reasons.get("no_sharded_corpus", 0) >= 1
        finally:
            node.close()

    def test_partial_configure_preserves_other_keys(self, mesh_serving):
        """`search.mesh.*` settings are process-wide: a node that sets
        ONE key must not clobber the others an earlier in-process node
        configured (the dispatcher warmup policy's rule)."""
        mesh_serving.configure(min_rows=1024)
        mesh_serving.configure(enabled=True)
        assert mesh_serving.min_rows() == 1024
        assert mesh_serving.stats()["num_shards"] == 8
        mesh_serving.configure(min_rows=None)   # explicit None = default
        assert mesh_serving.min_rows() == mesh_serving.DEFAULT_MIN_ROWS

    def test_dp_setting_partial_configure(self, mesh_serving):
        """`search.mesh.dp` follows `policy.configure`'s partial-update
        semantics: setting dp alone must not clobber the other keys, and
        explicit None resets it."""
        mesh_serving.configure(min_rows=2048)
        mesh_serving.configure(dp=2, num_shards=4)
        assert mesh_serving.min_rows() == 2048
        st = mesh_serving.stats()
        assert st["dp"] == 2 and st["num_shards"] == 4
        assert st["devices"] == {"total": 8, "shard_axis": 4,
                                 "dp_axis": 2}
        mesh_serving.configure(dp=None)   # explicit None = auto (dp=1)
        assert mesh_serving.stats()["dp"] == 1
        assert mesh_serving.min_rows() == 2048

    def test_knn_k_deeper_than_shard_reclassifies_router_stats(
            self, mesh_serving):
        """A mesh-accepted kNN dispatch that the k-deeper-than-shard
        guard then forces single-device must move its router decision
        over (the BM25 window guard's contract): `_nodes/stats
        indices.mesh` reflects where the dispatch actually ran."""
        node, rng = _make_node(tempfile.mkdtemp(), n=900, seed=15)
        try:
            store = node.indices.get("m").shards[0].vector_store
            fc = store.field("v")
            assert fc.mesh_state is not None
            deep_k = fc.mesh_state.layout.rows_per_shard + 1
            qv = rng.standard_normal(16).tolist()
            node.search("m", {"knn": {"field": "v", "query_vector": qv,
                                      "k": deep_k,
                                      "num_candidates": deep_k},
                              "size": 1})
            st = mesh_serving.stats()
            assert st["router"]["reasons"].get(
                "knn_k_deeper_than_shard", 0) >= 1
            assert st["router"]["mesh"] == 0
            assert store.knn_stats.get("mesh_searches", 0) == 0
        finally:
            node.close()


# ------------------------------------------------- dp > 1 (replicated)


def _oracle(vectors, queries, k):
    s, i = _single_device_knn(vectors, queries, k)
    return np.asarray(s), np.asarray(i)


class TestDpReplicatedServing:
    """The (dp=2, shard=4) replicated grid: byte parity on every route,
    concurrency on disjoint groups, replica-consistent merge
    graduation, and the strict-mode zero-recompile dp grid."""

    def test_dp_byte_parity_on_ragged_shards(self, mesh_serving_dp):
        """37 rows over 4 ragged shards, replicated across 2 dp groups:
        the full-mesh split route and BOTH group routes must be
        byte-identical to single-device (padding surfaces as (-inf, -1),
        never an aliased id, on every replica)."""
        from elasticsearch_tpu.parallel import mesh as mesh_lib

        rng = np.random.default_rng(21)
        vectors = rng.standard_normal((37, 16)).astype(np.float32)
        queries = rng.standard_normal((8, 16)).astype(np.float32)
        mesh = mesh_serving_dp.serving_mesh()
        state = ShardedFieldState(vectors, mesh, "cosine", "f32")
        s_ref, i_ref = _oracle(vectors, queries, 16)
        v = s_ref > -1e37
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)
        for route in (mesh,) + tuple(mesh_serving_dp.dp_groups()):
            q = jax.device_put(jnp.asarray(queries),
                               mesh_lib.query_sharding(route))
            s, g = distributed_knn_search(q, state.corpus_for(route), 16,
                                          route, metric="cosine",
                                          precision="f32")
            rows = state.map_ids(np.asarray(g))
            s = np.asarray(s)
            valid = s > -np.inf
            assert (rows[valid] >= 0).all() and (rows[valid] < 37).all()
            assert (rows[~valid] == -1).all()
            assert np.array_equal(rows[valid], i_ref[v])
            assert s[valid].tobytes() == s_ref[v].tobytes()

    def test_router_split_decisions_and_stats(self, mesh_serving_dp):
        """queue depth × corpus size drives the dp-vs-shard split, and
        `stats()` reports routes, reasons, and the round-robin group
        spread — the satellite's `_nodes/stats indices.mesh` contract."""
        from elasticsearch_tpu.parallel import mesh as mesh_lib

        pol = mesh_serving_dp
        # batch below dp -> group; queued -> group; idle large -> full
        m1 = pol.decide("knn", 5000, batch=1)
        m2 = pol.decide("knn", 5000, batch=8, queue_depth=2)
        m3 = pol.decide("knn", 5000, batch=8, queue_depth=0)
        assert mesh_lib.dp_size(m1) == 1
        assert mesh_lib.dp_size(m2) == 1
        assert mesh_lib.dp_size(m3) == 2
        # round-robin: consecutive group picks alternate groups
        assert m1 is not m2
        st = pol.stats()
        assert st["dp"] == 2
        assert st["devices"]["dp_axis"] == 2
        dp_st = st["router"]["dp"]
        assert dp_st["routes"] == {"shard": 1, "dp": 2}
        assert dp_st["reasons"]["batch_below_dp"] == 1
        assert dp_st["reasons"]["queue_pressure"] == 1
        assert dp_st["reasons"]["idle_large_corpus"] == 1
        assert set(dp_st["group_dispatches"]) == {"0", "1"}
        # the node stats section passes the dp fields through
        from elasticsearch_tpu.node import Node
        assert Node._mesh_stats_section()["dp"] == 2

    def test_concurrent_batches_on_disjoint_dp_groups(
            self, mesh_serving_dp):
        """Concurrent dispatches under queue pressure round-robin onto
        disjoint device groups and every one returns the single-device
        answer — the scheduling-concurrency contract the dp bench row
        measures."""
        import threading

        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)

        rng = np.random.default_rng(22)
        vectors = rng.standard_normal((800, 16)).astype(np.float32)
        mesh = mesh_serving_dp.serving_mesh()
        state = ShardedFieldState(vectors, mesh, "cosine", "f32")
        batches = [rng.standard_normal((8, 16)).astype(np.float32)
                   for _ in range(6)]
        oracles = [_oracle(vectors, qs, 10) for qs in batches]
        routes = [mesh_serving_dp.decide("knn", 800, batch=8,
                                         queue_depth=len(batches))
                  for _ in batches]
        assert all(mesh_lib.dp_size(r) == 1 for r in routes)
        assert len({id(r) for r in routes}) == 2  # both groups used
        results = [None] * len(batches)

        def run(idx):
            q = jax.device_put(jnp.asarray(batches[idx]),
                               mesh_lib.query_sharding(routes[idx]))
            s, g = distributed_knn_search(
                q, state.corpus_for(routes[idx]), 10, routes[idx],
                metric="cosine", precision="f32")
            results[idx] = (np.asarray(s), state.map_ids(np.asarray(g)))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(batches))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for (s, rows), (s_ref, i_ref) in zip(results, oracles):
            assert np.array_equal(rows, i_ref)
            assert s.tobytes() == s_ref.tobytes()
        spread = mesh_serving_dp.stats()["router"]["dp"][
            "group_dispatches"]
        assert len(spread) == 2  # dispatches landed on both groups

    def test_replica_consistent_merge_graduation(self, mesh_serving_dp,
                                                 monkeypatch):
        """Generational merge graduation under dp > 1: a search
        dispatched BEFORE the install keeps one coherent (old) snapshot;
        after the install every dp replica serves the merged corpus
        byte-identically — a merge can never leave two groups on
        different corpus versions."""
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)
        from elasticsearch_tpu.serving.batcher import CostModel

        monkeypatch.setattr(CostModel, "prefer_host",
                            staticmethod(lambda *a, **kw: False))
        node, rng = _make_node(tempfile.mkdtemp(), n=600, seed=23)
        try:
            store = node.indices.get("m").shards[0].vector_store
            old_ms = store.field("v").mesh_state
            assert old_ms is not None
            assert old_ms.mesh is mesh_serving_dp.serving_mesh()
            old_vectors = None  # oracle comes from the engine below

            # ingest a delta and refresh: seals an L0 generation; the
            # base's sharded copy graduates at MERGE time
            ops = []
            for i in range(600, 700):
                ops.append({"index": {"_index": "m", "_id": str(i)}})
                ops.append({"body": "x", "tag": "even",
                            "v": rng.standard_normal(16).tolist()})
            node.bulk(ops)
            node.indices.get("m").refresh()
            gc = store._gens["v"]
            snap_before = gc.snapshot()       # dispatch-before-install
            assert len(snap_before.generations) >= 2
            assert gc.force_merge()           # graduates the new base
            snap_after = gc.snapshot()
            base = snap_after.generations[0]
            assert base.n_rows == 700
            new_ms = base.mesh_state
            assert new_ms is not None and new_ms.n_rows == 700

            queries = rng.standard_normal((8, 16)).astype(np.float32)
            # oracle on the store's own serving dtype (bf16), so replica
            # boards are byte-comparable to it
            ref_corpus = knn_ops.build_corpus(
                np.asarray(base.host_vectors, dtype=np.float32),
                metric="cosine", dtype="bf16")
            s_ref, i_ref = knn_ops.knn_search(
                jnp.asarray(queries), ref_corpus, 10, metric="cosine",
                precision="bf16")
            s_ref, i_ref = np.asarray(s_ref), np.asarray(i_ref)
            boards = []
            for route in ((new_ms.mesh,)
                          + tuple(mesh_serving_dp.dp_groups())):
                q = jax.device_put(jnp.asarray(queries),
                                   mesh_lib.query_sharding(route))
                s, g = distributed_knn_search(
                    q, new_ms.corpus_for(route), 10, route,
                    metric="cosine", precision="bf16")
                boards.append((np.asarray(s),
                               new_ms.map_ids(np.asarray(g))))
            # every replica view byte-identical to each other AND to the
            # single-device oracle over the merged host vectors
            for s, rows in boards:
                assert np.array_equal(rows, i_ref)
                assert s.tobytes() == s_ref.tobytes()

            # the pre-install snapshot still serves its own coherent
            # version: the old base's sharded copy reads valid buffers
            # (copy-on-write install) and answers for the OLD corpus
            old_base = snap_before.generations[0]
            assert old_base.mesh_state is old_ms
            group0 = mesh_serving_dp.dp_groups()[0]
            q = jax.device_put(jnp.asarray(queries),
                               mesh_lib.query_sharding(group0))
            s_old, g_old = distributed_knn_search(
                q, old_ms.corpus_for(group0), 10, group0,
                metric="cosine", precision="bf16")
            old_ref_corpus = knn_ops.build_corpus(
                np.asarray(old_base.host_vectors, dtype=np.float32),
                metric="cosine", dtype="bf16")
            s_old_ref, i_old_ref = knn_ops.knn_search(
                jnp.asarray(queries), old_ref_corpus, 10,
                metric="cosine", precision="bf16")
            assert np.array_equal(old_ms.map_ids(np.asarray(g_old)),
                                  np.asarray(i_old_ref))
            assert np.asarray(s_old).tobytes() == \
                np.asarray(s_old_ref).tobytes()
        finally:
            node.close()

    def test_strict_zero_recompile_second_pass_over_dp_grid(
            self, mesh_serving_dp):
        """Warmup covers the full-mesh buckets AND every dp-group
        submesh; a strict-mode second pass over the whole dp grid (both
        routes, interactive buckets) must compile nothing."""
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)

        rng = np.random.default_rng(24)
        vectors = rng.standard_normal((512, 16)).astype(np.float32)
        mesh = mesh_serving_dp.serving_mesh()
        state = ShardedFieldState(vectors, mesh, "cosine", "f32")
        dispatch.DISPATCH.warmup(state.warmup_entries(16),
                                 background=False)
        before = dispatch.stats(per_bucket=False)
        old_strict = dispatch.DISPATCH.strict
        dispatch.DISPATCH.strict = True
        try:
            for route in (mesh,) + tuple(mesh_serving_dp.dp_groups()):
                for b in (8, 16):
                    qs = rng.standard_normal((b, 16)).astype(np.float32)
                    q = jax.device_put(
                        jnp.asarray(qs),
                        __import__("elasticsearch_tpu.parallel.mesh",
                                   fromlist=["query_sharding"])
                        .query_sharding(route))
                    distributed_knn_search(q, state.corpus_for(route),
                                           10, route, metric="cosine",
                                           precision="bf16")
        finally:
            dispatch.DISPATCH.strict = old_strict
        after = dispatch.stats(per_bucket=False)
        assert after["compiles"] == before["compiles"]
        assert after["out_of_grid_compiles"] == \
            before["out_of_grid_compiles"]
        assert after["hits"] > before["hits"]

    def test_dp_serving_through_store_parity(self, mesh_serving_dp,
                                             monkeypatch):
        """End-to-end through Node.search on the (dp=2, shard=4) mesh:
        responses byte-identical to the mesh-off single-device path, and
        the mesh router actually routed (the store feeds batch + live
        queue depth into the dp split)."""
        from elasticsearch_tpu.serving.batcher import CostModel

        monkeypatch.setattr(CostModel, "prefer_host",
                            staticmethod(lambda *a, **kw: False))
        node, rng = _make_node(tempfile.mkdtemp(), n=800, seed=25)
        try:
            qv = rng.standard_normal(16).tolist()
            body = {"knn": {"field": "v", "query_vector": qv, "k": 10,
                            "num_candidates": 50}, "size": 10}
            dp_resp = node.search("m", dict(body))
            st = mesh_serving_dp.stats()
            assert st["router"]["mesh"] >= 1
            assert st["dp"] == 2
            store = node.indices.get("m").shards[0].vector_store
            assert store.knn_stats["mesh_searches"] >= 1
            assert store.last_knn_phases["engine"] == "tpu_mesh"
            assert store.last_knn_phases["mesh_dp"] == 2
            mesh_serving_dp.configure(enabled=False)
            one_resp = node.search("m", dict(body))
            assert _strip_took(dp_resp) == _strip_took(one_resp)
        finally:
            node.close()
