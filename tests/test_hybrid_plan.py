"""Fused hybrid execution plan (search/hybrid_plan.py).

Two contracts gate the fused path:

1. PARITY — for every supported body shape, the fused plan's response is
   byte-identical (modulo `took`) to the legacy two-phase path it
   replaces, which stays available as the oracle behind
   `__rrf_two_phase__`. Fixed seeds; filtered kNN leg, pagination,
   operator=and, generic legs, sub_searches all covered.

2. SATURATION — the bounded admission queue sheds overload as 429
   (EsRejectedExecutionError) and the queue depth stays at its configured
   bound instead of growing into a p99 tail.
"""

import json
import tempfile
import threading

import numpy as np
import pytest

from elasticsearch_tpu.common.threadpool import EsRejectedExecutionError
from elasticsearch_tpu.node import Node


@pytest.fixture(scope="module")
def node():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    rng = np.random.default_rng(7)
    n = Node(tempfile.mkdtemp())
    n.create_index_with_templates("h", mappings={"properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank_n": {"type": "integer"},
        "v": {"type": "dense_vector", "dims": 8}}})
    ops = []
    for i in range(400):
        ops.append({"index": {"_index": "h", "_id": str(i)}})
        ops.append({"body": " ".join(rng.choice(list("abcdefg"), 5)),
                    "tag": "even" if i % 2 == 0 else "odd",
                    "rank_n": i,
                    "v": rng.standard_normal(8).tolist()})
    n.bulk(ops)
    n.indices.get("h").refresh()
    yield n, rng
    n.close()


def _compare(node, body):
    fused = node.search("h", dict(body))
    oracle = node.search("h", {**body, "__rrf_two_phase__": True})
    fused.pop("took")
    oracle.pop("took")
    assert json.dumps(fused, sort_keys=True) \
        == json.dumps(oracle, sort_keys=True)
    return fused


class TestParity:
    def _base(self, rng, **over):
        body = {"rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 50}},
                "query": {"match": {"body": "a b"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 50, "num_candidates": 50},
                "size": 10}
        body.update(over)
        return body

    def test_basic_hybrid(self, node):
        n, rng = node
        resp = _compare(n, self._base(rng))
        assert len(resp["hits"]["hits"]) == 10
        assert resp["hits"]["hits"][0]["_score"] > 0

    def test_source_false(self, node):
        n, rng = node
        resp = _compare(n, self._base(rng, _source=False))
        assert "_source" not in resp["hits"]["hits"][0]

    def test_pagination(self, node):
        n, rng = node
        base = self._base(rng)
        page0 = _compare(n, {**base, "from": 0, "size": 5})
        page1 = _compare(n, {**base, "from": 5, "size": 5})
        ids0 = [h["_id"] for h in page0["hits"]["hits"]]
        ids1 = [h["_id"] for h in page1["hits"]["hits"]]
        assert not set(ids0) & set(ids1)
        full = _compare(n, {**base, "size": 10})
        assert [h["_id"] for h in full["hits"]["hits"]] == ids0 + ids1

    def test_filtered_knn_leg(self, node):
        n, rng = node
        body = self._base(rng)
        body["knn"]["filter"] = {"term": {"tag": "even"}}
        _compare(n, body)

    def test_operator_and_lexical_leg(self, node):
        n, rng = node
        _compare(n, self._base(rng, query={"match": {
            "body": {"query": "a b c", "operator": "and"}}}))

    def test_generic_leg_range_query(self, node):
        n, rng = node
        _compare(n, self._base(rng, query={"range": {
            "rank_n": {"gte": 100, "lt": 300}}}))

    def test_sub_searches(self, node):
        n, rng = node
        _compare(n, {"rank": {"rrf": {"rank_window_size": 40}},
                     "sub_searches": [
                         {"query": {"match": {"body": "a"}}},
                         {"query": {"match": {"body": "b c"}}},
                         {"query": {"term": {"tag": "even"}}}],
                     "size": 10})

    def test_knn_defaults_num_candidates_only(self, node):
        """knn with only num_candidates: k defaults to 10 (parse_query
        semantics), NOT to num_candidates — and num_candidates clamps up
        to k, exactly like the oracle's KnnQuery."""
        n, rng = node
        resp = _compare(n, {
            "rank": {"rrf": {}},
            "query": {"match": {"body": "a"}},
            "knn": {"field": "v",
                    "query_vector": rng.standard_normal(8).tolist(),
                    "num_candidates": 40},
            "size": 10})
        assert resp["hits"]["hits"]

    def test_knn_list_is_one_leg_per_clause(self, node):
        n, rng = node
        resp = _compare(n, {
            "rank": {"rrf": {}},
            "knn": [{"field": "v",
                     "query_vector": rng.standard_normal(8).tolist(),
                     "k": 20},
                    {"field": "v",
                     "query_vector": rng.standard_normal(8).tolist(),
                     "k": 20}],
            "size": 10})
        assert len(resp["hits"]["hits"]) == 10

    def test_knn_wrong_dims_is_400(self, node):
        n, _ = node
        from elasticsearch_tpu.common.errors import IllegalArgumentError
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v", "query_vector": [0.1, 0.2], "k": 5},
                "size": 5}
        with pytest.raises(IllegalArgumentError, match="dims"):
            n.search("h", dict(body))
        with pytest.raises(IllegalArgumentError, match="dims"):
            n.search("h", {**body, "__rrf_two_phase__": True})

    def test_deleted_index_evicts_executor(self, node):
        n, rng = node
        import tempfile
        from elasticsearch_tpu.node import Node
        n2 = Node(tempfile.mkdtemp())
        n2.create_index_with_templates("tmp_h", mappings={"properties": {
            "body": {"type": "text"},
            "v": {"type": "dense_vector", "dims": 4}}})
        n2.bulk([{"index": {"_index": "tmp_h", "_id": "1"}},
                 {"body": "a", "v": [0.1, 0.2, 0.3, 0.4]}])
        n2.indices.get("tmp_h").refresh()
        n2.search("tmp_h", {"rank": {"rrf": {}},
                            "query": {"match": {"body": "a"}},
                            "knn": {"field": "v",
                                    "query_vector": [0.1, 0.2, 0.3, 0.4],
                                    "k": 5},
                            "size": 5})
        assert "tmp_h" in n2._hybrid
        n2.indices.delete_index("tmp_h")
        n2._hybrid_stats_section()  # any hybrid entry point sweeps
        assert "tmp_h" not in n2._hybrid
        n2.close()

    def test_docvalue_fields_passthrough(self, node):
        n, rng = node
        resp = _compare(n, self._base(rng, docvalue_fields=["rank_n"]))
        assert "rank_n" in resp["hits"]["hits"][0]["fields"]

    def test_batched_concurrent_matches_sequential(self, node):
        """8 clients coalescing through the hybrid batcher must see the
        same hits as the same bodies run one at a time."""
        n, rng = node
        bodies = [self._base(rng) for _ in range(8)]
        sequential = [n.search("h", dict(b)) for b in bodies]
        results = [None] * len(bodies)

        def client(i):
            results[i] = n.search("h", dict(bodies[i]))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(bodies))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for seq, conc in zip(sequential, results):
            assert [h["_id"] for h in seq["hits"]["hits"]] \
                == [h["_id"] for h in conc["hits"]["hits"]]
            assert [h["_score"] for h in seq["hits"]["hits"]] \
                == [h["_score"] for h in conc["hits"]["hits"]]


class TestPlanCache:
    def test_hit_vs_miss(self, node):
        n, rng = node
        ex = n._hybrid_executor(n.indices.get("h"))
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 20},
                "size": 5,
                # the shard request cache would serve the repeat before
                # the planner runs; this test is about the PLAN cache
                "request_cache": False}
        misses0 = ex.stats["plan_cache_misses"]
        hits0 = ex.stats["plan_cache_hits"]
        r1 = n.search("h", dict(body))
        assert ex.stats["plan_cache_misses"] == misses0 + 1
        r2 = n.search("h", dict(body))  # identical body → cache hit
        assert ex.stats["plan_cache_hits"] == hits0 + 1
        assert ex.stats["plan_cache_misses"] == misses0 + 1
        r1.pop("took"), r2.pop("took")
        assert r1 == r2
        # a different shape misses again
        n.search("h", {**body, "size": 6})
        assert ex.stats["plan_cache_misses"] == misses0 + 2

    def test_same_shape_different_values_hits(self, node):
        """The r06 bench regression: 108 structurally identical rank.rrf
        bodies recorded plan_cache_hits: 0 because the key hashed the
        query VECTOR and match TEXT. The key now scrubs per-query values:
        a fixed shape with varying values must miss once and hit
        thereafter — and still return the right per-query results."""
        n, rng = node
        ex = n._hybrid_executor(n.indices.get("h"))

        def body(text, vec):
            return {"rank": {"rrf": {"rank_window_size": 37}},
                    "query": {"match": {"body": text}},
                    "knn": {"field": "v", "query_vector": vec, "k": 19},
                    "size": 7}

        probes = [("a b", rng.standard_normal(8).tolist())
                  for _ in range(6)] + \
                 [("c d", rng.standard_normal(8).tolist())
                  for _ in range(6)]
        misses0 = ex.stats["plan_cache_misses"]
        hits0 = ex.stats["plan_cache_hits"]
        fused = [n.search("h", body(t, v)) for t, v in probes]
        assert ex.stats["plan_cache_misses"] == misses0 + 1, \
            "structurally identical bodies must share ONE plan"
        assert ex.stats["plan_cache_hits"] == hits0 + len(probes) - 1
        # hit-rate: steady state ≥ 90% for this workload
        hits = ex.stats["plan_cache_hits"] - hits0
        assert hits / len(probes) > 0.9
        # correctness: each cached-plan result == the two-phase oracle
        # for ITS OWN values (a stale plan would leak another query's
        # vector/text into the legs)
        for (t, v), resp in zip(probes, fused):
            oracle = n.search("h", {**body(t, v),
                                    "__rrf_two_phase__": True})
            resp = dict(resp)
            resp.pop("took"), oracle.pop("took")
            assert json.dumps(resp, sort_keys=True) \
                == json.dumps(oracle, sort_keys=True)

    def test_wrong_dims_still_400_on_cached_plan(self, node):
        """Dims validation moved from plan compile to per-query bind; a
        cached plan must still 400 a mis-sized vector."""
        from elasticsearch_tpu.common.errors import IllegalArgumentError
        n, rng = node
        good = {"rank": {"rrf": {}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 21},
                "size": 4}
        n.search("h", dict(good))  # populate the plan cache
        bad = dict(good)
        bad["knn"] = {**good["knn"],
                      "query_vector": rng.standard_normal(5).tolist()}
        with pytest.raises(IllegalArgumentError):
            n.search("h", bad)

    def test_profile_reports_cache_state_and_phases(self, node):
        n, rng = node
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "b"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 20},
                "size": 5, "profile": True}
        p1 = n.search("h", dict(body))["profile"]["hybrid"]
        assert p1["plan_cache"] == "miss"
        p2 = n.search("h", dict(body))["profile"]["hybrid"]
        assert p2["plan_cache"] == "hit"
        for phase in ("plan_nanos", "score_nanos", "fuse_nanos",
                      "hydrate_nanos"):
            assert p2["breakdown"][phase] >= 0
        kinds = {leg["type"] for leg in p2["legs"]}
        assert kinds == {"lexical_device", "knn_device"}

    def test_nodes_stats_hybrid_section(self, node):
        n, _ = node
        section = n.local_node_stats()["indices"]["hybrid"]
        assert section["searches"] > 0
        assert section["plan_cache_hits"] > 0
        assert section["score_nanos"] > 0

    def test_tail_attribution_in_profile_and_stats(self, node):
        """Satellite of the continuous-batching PR: the closed-loop tail
        must be diagnosable as queueing vs device vs hydrate from the
        profile and `_nodes/stats indices.hybrid` alone, with the
        scheduler counters (topups/deadline_sheds/overlap_hits) along."""
        n, rng = node
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "c"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 15},
                "size": 5, "profile": True}
        p = n.search("h", dict(body))["profile"]["hybrid"]
        assert p["breakdown"]["queue_wait_nanos"] >= 0
        assert p["breakdown"]["device_dispatch_nanos"] > 0
        assert p["breakdown"]["device_sync_nanos"] >= 0
        # the split sums to score time: launch share + deferred sync
        assert p["breakdown"]["device_dispatch_nanos"] \
            + p["breakdown"]["device_sync_nanos"] \
            == p["breakdown"]["score_nanos"]
        assert set(p["scheduler"]) >= {"topups", "deadline_sheds",
                                       "overlap_hits"}
        section = n.local_node_stats()["indices"]["hybrid"]
        assert section["dispatch_nanos"] > 0
        assert section["sync_nanos"] >= 0
        assert section["queue_wait_nanos"] >= 0
        # score preserved as the dispatch+sync sum for cross-round
        # comparability
        assert section["score_nanos"] == section["dispatch_nanos"] \
            + section["sync_nanos"]
        sched = section["scheduler"]
        assert sched["pipelined_batches"] >= 1
        assert sched["deadline_sheds"] >= 0


class TestSaturation:
    def test_bounded_queue_sheds_429(self, node):
        """Saturate a tiny admission queue: total = served + shed, queue
        depth never exceeds the bound, and shedding is the 429-typed
        error, not a timeout or a tail."""
        n, rng = node
        svc = n.indices.get("h")
        from elasticsearch_tpu.search.hybrid_plan import HybridExecutor
        ex = HybridExecutor(n, svc, max_batch=2, max_queue_depth=3,
                            deadline_ms=None)
        gate = threading.Event()
        # stall the DISPATCH stage: the runner holds the scheduler lock
        # inside dispatch_fn, so everything behind it must queue (and the
        # depth bound must shed) exactly as a slow device would force
        inner = ex.batcher._dispatch_fn

        def slow_dispatch(bodies):
            gate.wait(10)
            return inner(bodies)

        ex.batcher._dispatch_fn = slow_dispatch
        n._hybrid["h"] = ex
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 10},
                "size": 5}
        served, shed = [], []

        def client(i):
            try:
                served.append(n.search("h", dict(body)))
            except EsRejectedExecutionError as e:
                assert e.status == 429
                shed.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        import time
        time.sleep(0.3)  # let every client enqueue or get rejected
        gate.set()
        for t in threads:
            t.join(30)
        n._hybrid.pop("h", None)
        assert len(served) + len(shed) == 12
        assert len(shed) >= 1              # overload actually shed
        assert len(served) >= 4            # bounded queue still served
        st = ex.batcher.stats
        assert st["max_depth_seen"] <= 3   # the bound held
        assert st["rejected_depth"] == len(shed)
        for resp in served:
            assert resp["hits"]["hits"]

    def test_deadline_sheds_stale_requests(self, node):
        n, rng = node
        svc = n.indices.get("h")
        from elasticsearch_tpu.search.hybrid_plan import HybridExecutor
        # topup=False: the in-flight top-up window would otherwise claim
        # the late arrivals into the first (stalled) batch — this test
        # wants them left in the queue to age past the deadline
        ex = HybridExecutor(n, svc, max_batch=4, max_queue_depth=64,
                            deadline_ms=50.0, topup=False)
        gate = threading.Event()
        inner = ex.batcher._dispatch_fn

        def slow_dispatch(bodies):
            gate.wait(10)
            return inner(bodies)

        ex.batcher._dispatch_fn = slow_dispatch
        n._hybrid["h"] = ex
        body = {"rank": {"rrf": {}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 10},
                "size": 5}
        outcomes = []

        def client():
            try:
                n.search("h", dict(body))
                outcomes.append("ok")
            except EsRejectedExecutionError:
                outcomes.append("shed")

        threads = [threading.Thread(target=client) for _ in range(6)]
        for t in threads:
            t.start()
        import time
        time.sleep(0.3)  # all queued requests age past the 50ms deadline
        gate.set()
        for t in threads:
            t.join(30)
        n._hybrid.pop("h", None)
        # the first runner's own batch drains before the stall in this
        # design (it drained pre-gate); everything queued behind it aged
        # out and must have been shed, not served late
        assert outcomes.count("shed") >= 1
        assert ex.batcher.stats["shed_deadline"] >= 1


class TestRejectionMapsTo429:
    def test_rest_layer_maps_rejection(self, node):
        n, _ = node
        err = EsRejectedExecutionError("queue full")
        assert err.status == 429
