"""TCP transport: wire codec, RPC semantics, and a real multi-node cluster
over loopback sockets (the production analog of test_multi_node.py, which
runs the same ClusterNode stack under the deterministic simulator)."""

import asyncio

import pytest

from elasticsearch_tpu.cluster.cluster_node import ClusterNode
from elasticsearch_tpu.cluster.coordination import bootstrap_state
from elasticsearch_tpu.cluster.state import ShardRoutingEntry
from elasticsearch_tpu.transport import (
    AsyncioScheduler, ConnectTransportError, RemoteTransportError,
    TcpTransportService, WireFormatError, channel_type_for, decode_frames,
    encode_frame, encode_ping,
)
from elasticsearch_tpu.transport.wire import (
    STATUS_COMPRESS, STATUS_ERROR, STATUS_REQUEST, WIRE_VERSION,
)


# --------------------------------------------------------------- wire codec

def test_frame_roundtrip_request():
    payload = {"sender": "n1", "request": {"doc": {"title": "hello", "n": 3},
                                           "vals": [1.5, None, True, b"\x00\x01"]}}
    buf = bytearray(encode_frame(42, STATUS_REQUEST, WIRE_VERSION,
                                 "indices:data/write/primary", payload))
    frames = decode_frames(buf)
    assert len(frames) == 1 and not buf
    rid, status, version, action, decoded = frames[0]
    assert rid == 42 and status & STATUS_REQUEST
    assert action == "indices:data/write/primary"
    assert decoded == payload


def test_frame_compression_kicks_in_above_threshold():
    big = {"sender": "n1", "request": {"blob": "x" * 100_000}}
    raw = encode_frame(1, STATUS_REQUEST, WIRE_VERSION, "a", big)
    assert len(raw) < 10_000  # zlib crushed the repeated payload
    buf = bytearray(raw)
    (_, status, _, _, decoded), = decode_frames(buf)
    assert status & STATUS_COMPRESS
    assert decoded == big


def test_frame_incremental_decode_and_ping():
    f1 = encode_frame(7, STATUS_REQUEST, WIRE_VERSION, "act", {"a": 1})
    f2 = encode_ping()
    f3 = encode_frame(8, 0, WIRE_VERSION, None, {"ok": True})
    stream = f1 + f2 + f3
    buf = bytearray()
    seen = []
    for i in range(0, len(stream), 5):  # drip-feed 5 bytes at a time
        buf.extend(stream[i:i + 5])
        seen.extend(decode_frames(buf))
    assert [s[0] for s in seen] == [7, 0, 8]
    assert not buf


def test_frame_bad_marker_rejected():
    with pytest.raises(WireFormatError):
        decode_frames(bytearray(b"XXjunkjunkjunk"))


def test_channel_type_routing():
    assert channel_type_for("internal:index/shard/recovery/start_recovery") == "recovery"
    assert channel_type_for("indices:data/write/primary") == "bulk"
    assert channel_type_for("internal:cluster/coordination/publish") == "state"
    assert channel_type_for("indices:data/read/query") == "reg"


# ------------------------------------------------------------ RPC semantics

def run(coro, timeout=30):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def make_pair():
    a = TcpTransportService("a", keepalive_interval_ms=200)
    b = TcpTransportService("b", keepalive_interval_ms=200)
    await a.bind()
    await b.bind()
    a.add_peer_address("b", *b.bound_address)
    b.add_peer_address("a", *a.bound_address)
    return a, b


async def wait_for(box, key, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while key not in box:
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"no [{key}] within {timeout}s: {box}")
        await asyncio.sleep(0.005)
    return box[key]


def test_request_response_over_sockets():
    async def body():
        a, b = await make_pair()
        b.register("b", "echo", lambda sender, req, respond: respond(
            {"echoed": req, "from": sender}))
        box = {}
        a.send("a", "b", "echo", {"msg": "hi", "n": 1},
               on_response=lambda r: box.update(r=r))
        r = await wait_for(box, "r")
        assert r == {"echoed": {"msg": "hi", "n": 1}, "from": "a"}
        # second request reuses the channel
        a.send("a", "b", "echo", {"msg": "again"},
               on_response=lambda r2: box.update(r2=r2))
        r2 = await wait_for(box, "r2")
        assert r2["echoed"]["msg"] == "again"
        assert a.stats["connections_opened"] == 1
        await a.close(); await b.close()
    run(body())


def test_remote_exception_propagates_as_failure():
    async def body():
        a, b = await make_pair()
        def boom(sender, req, respond):
            raise ValueError("shard is closed")
        b.register("b", "boom", boom)
        box = {}
        a.send("a", "b", "boom", {}, on_failure=lambda e: box.update(e=e))
        e = await wait_for(box, "e")
        assert isinstance(e, RemoteTransportError)
        assert "shard is closed" in str(e)
        await a.close(); await b.close()
    run(body())


def test_unknown_action_and_unknown_node():
    async def body():
        a, b = await make_pair()
        box = {}
        a.send("a", "b", "no/such/action", {},
               on_failure=lambda e: box.update(e1=e))
        e1 = await wait_for(box, "e1")
        assert "no handler" in str(e1)
        a.send("a", "ghost", "echo", {}, on_failure=lambda e: box.update(e2=e))
        e2 = await wait_for(box, "e2")
        assert isinstance(e2, ConnectTransportError)
        await a.close(); await b.close()
    run(body())


def test_request_timeout_fires():
    async def body():
        a, b = await make_pair()
        b.register("b", "slow", lambda s, r, respond: None)  # never responds
        box = {}
        a.send("a", "b", "slow", {}, on_failure=lambda e: box.update(e=e),
               timeout_ms=100)
        e = await wait_for(box, "e")
        assert "timed out" in str(e)
        await a.close(); await b.close()
    run(body())


def test_local_send_skips_sockets():
    async def body():
        a = TcpTransportService("a")
        await a.bind()
        a.register("a", "echo", lambda s, r, respond: respond({"ok": True}))
        box = {}
        a.send("a", "a", "echo", {}, on_response=lambda r: box.update(r=r))
        r = await wait_for(box, "r")
        assert r == {"ok": True}
        assert a.stats["tx_count"] == 0  # never hit the wire
        await a.close()
    run(body())


def test_handshake_rejects_wrong_node_identity():
    async def body():
        a = TcpTransportService("a")
        imposter = TcpTransportService("not-b")
        await a.bind(); await imposter.bind()
        a.add_peer_address("b", *imposter.bound_address)
        box = {}
        a.send("a", "b", "echo", {}, on_failure=lambda e: box.update(e=e))
        e = await wait_for(box, "e")
        assert "expected node" in str(e) or "handshake" in str(e).lower()
        await a.close(); await imposter.close()
    run(body())


def test_channel_close_fails_inflight_requests():
    """A dropped connection must fail pending requests immediately, not wait
    for (or never hit) the timeout."""
    async def body():
        a, b = await make_pair()
        b.register("b", "slow", lambda s, r, respond: None)  # never responds
        box = {}
        a.send("a", "b", "slow", {}, on_failure=lambda e: box.update(e=e),
               timeout_ms=None)  # no timeout: only channel death can fail it
        await asyncio.sleep(0.1)
        await b.close()  # peer dies with the request in flight
        e = await wait_for(box, "e")
        assert isinstance(e, ConnectTransportError)
        assert "in flight" in str(e)
        await a.close()
    run(body())


# ----------------------------------------------- full cluster over real TCP

class TcpCluster:
    def __init__(self, tmp_path, loop, n_nodes=3):
        self.loop = loop
        ids = [f"n{i}" for i in range(n_nodes)]
        self.transports = {}
        for nid in ids:
            self.transports[nid] = TcpTransportService(nid, loop=loop)
        loop.run_until_complete(asyncio.gather(
            *[t.bind() for t in self.transports.values()]))
        for nid, t in self.transports.items():
            for other, ot in self.transports.items():
                if other != nid:
                    t.add_peer_address(other, *ot.bound_address)
        initial = bootstrap_state(ids)
        self.nodes = {}
        for i, nid in enumerate(ids):
            sched = AsyncioScheduler(loop, seed=i)
            self.nodes[nid] = ClusterNode(
                nid, str(tmp_path / nid), self.transports[nid], sched,
                seed_peers=[p for p in ids if p != nid], initial_state=initial)
        for n in self.nodes.values():
            n.start()

    def run_until(self, cond, max_s=30.0):
        deadline = self.loop.time() + max_s
        while self.loop.time() < deadline:
            self.loop.run_until_complete(asyncio.sleep(0.02))
            if cond():
                return True
        return cond()

    def master(self):
        for n in self.nodes.values():
            if n.is_master and not n.coordinator.stopped:
                return n
        return None

    def call(self, fn, *args, **kw):
        box = {}
        fn(*args, **kw, on_done=lambda r: box.update(r=r))
        assert self.run_until(lambda: "r" in box), f"no response from {fn.__name__}"
        return box["r"]

    def close(self):
        for n in self.nodes.values():
            if not n.coordinator.stopped:
                n.stop()
        self.loop.run_until_complete(asyncio.gather(
            *[t.close() for t in self.transports.values()]))


def test_full_cluster_over_tcp(tmp_path):
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    try:
        c = TcpCluster(tmp_path, loop, n_nodes=3)
        assert c.run_until(lambda: c.master() is not None), "no master over TCP"

        any_node = next(iter(c.nodes.values()))
        any_node.client_create_index(
            "docs", settings={"index.number_of_shards": 2,
                              "index.number_of_replicas": 1},
            mappings={"properties": {"title": {"type": "text"},
                                     "n": {"type": "long"}}})

        def all_started():
            shards = any_node.cluster_state.shards_of("docs")
            return bool(shards) and all(
                s.state == ShardRoutingEntry.STARTED for s in shards)
        assert c.run_until(all_started), "shards did not start over TCP"

        for i in range(12):
            r = c.call(any_node.client_write, "docs",
                       {"type": "index", "id": str(i),
                        "source": {"title": f"doc number {i}", "n": i}})
            assert r.get("result") in ("created", "updated"), r

        for node in c.nodes.values():
            node.refresh_all()
        resp = c.call(any_node.client_search, "docs",
                      {"query": {"match_all": {}}, "size": 20})
        assert resp["hits"]["total"]["value"] == 12

        # the data actually crossed sockets: some node sent bytes
        assert any(t.stats["tx_bytes"] > 0 for t in c.transports.values())
        c.close()
    finally:
        loop.close()


# ------------------------------------------- connection profile + RTT feed

def test_connection_profile_widens_reg_only_under_concurrency():
    """Serial traffic stays on one socket (pinned above); CONCURRENT
    requests widen to the profile's reg allowance (2) and no further —
    the third in-flight request round-robins over the busy pair."""
    async def body():
        a, b = await make_pair()
        held = []
        b.register("b", "hold", lambda s, r, respond: held.append(respond))
        boxes = [{} for _ in range(3)]
        for i, box in enumerate(boxes):
            a.send("a", "b", "hold", {"i": i},
                   on_response=lambda r, box=box: box.update(r=r))
        deadline = asyncio.get_event_loop().time() + 5
        while len(held) < 3:
            assert asyncio.get_event_loop().time() < deadline, held
            await asyncio.sleep(0.005)
        assert a.stats["connections_opened"] == 2
        for respond in list(held):
            respond({"ok": True})
        for box in boxes:
            await wait_for(box, "r")
        # all channels idle again: a follow-up request reuses, not opens
        held.clear()
        box = {}
        a.send("a", "b", "hold", {}, on_response=lambda r: box.update(r=r))
        while not held:
            await asyncio.sleep(0.005)
        held[0]({"ok": True})
        await wait_for(box, "r")
        assert a.stats["connections_opened"] == 2
        await a.close(); await b.close()
    run(body())


def test_recovery_stream_does_not_hol_block_queries():
    """A recovery transfer saturating its channel must not head-of-line
    block query fan-out: recovery actions ride their OWN socket."""
    async def body():
        a, b = await make_pair()
        rec_held = []
        b.register("b", "internal:index/shard/recovery/chunk",
                   lambda s, r, respond: rec_held.append(respond))
        b.register("b", "echo", lambda s, r, respond: respond({"ok": True}))
        box = {}
        a.send("a", "b", "internal:index/shard/recovery/chunk",
               {"blob": "x" * 1000}, on_response=lambda r: box.update(rec=r))
        a.send("a", "b", "echo", {}, on_response=lambda r: box.update(q=r))
        await wait_for(box, "q")
        assert "rec" not in box      # the query finished FIRST
        assert a.stats["connections_opened"] == 2  # recovery + reg sockets
        rec_held[0]({"done": True})
        await wait_for(box, "rec")
        await a.close(); await b.close()
    run(body())


def test_rtt_comes_from_control_exchanges_not_service_time():
    """The RTT EWMA feeds the dispatch cost router's wire term; it must
    sample only O(1) control exchanges (handshake/ping). A slow handler
    (service time) must NOT inflate it — the service EWMA already
    carries that, and double-counting would poison the device-leg
    estimate."""
    async def body():
        a, b = await make_pair()
        loop = asyncio.get_event_loop()
        b.register("b", "work", lambda s, r, respond: loop.call_later(
            0.25, lambda: respond({"ok": True})))
        box = {}
        a.send("a", "b", "work", {}, on_response=lambda r: box.update(r=r))
        await wait_for(box, "r")
        rtt = a.rtt_ms("b")
        assert rtt is not None and rtt < 150, \
            f"loopback handshake RTT, not the 250ms service time: {rtt}"
        await a.close(); await b.close()
    run(body())
