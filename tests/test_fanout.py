"""Cross-node serving: deadline-propagating scatter-gather under faults.

The four contracts the fan-out subsystem (serving/fanout.py) must hold:

* expired budget → PARTIAL results: `timed_out: true`, correct
  `_shards.failed`, hits from the shards that answered — never a hang.
* dead node → the per-shard timers complete the phase; the response
  arrives within the budget with the dead node's shards counted failed.
* slow node + propagated deadline → the REMOTE node sheds the
  sub-request at its own admission layer (the continuous batcher's EDF
  queue), and the coordinator attributes it as a shed — its own backstop
  timer never fires.
* fault harness installed but idle → byte-identical accumulator behavior
  to a bare cluster (the wrapper must be invisible at zero faults).

All scenarios run with the `FaultInjectingTransport` wrapper
(testing/faults.py) injecting the drop/delay/kill behaviors. The core
fault scenarios run TWICE — once on the deterministic simulator
(virtual clock) and once over real TCP sockets (`transport/tcp.py`,
wall clock) — proving the fan-out contracts are properties of the
serving code, not artifacts of the simulated transport. A final parity
test pins the sim and socket paths to byte-identical kNN responses.
"""

import asyncio
import json

import numpy as np
import pytest

from elasticsearch_tpu.cluster.cluster_node import (
    QUERY_SHARD, ClusterNode,
)
from elasticsearch_tpu.cluster.coordination import bootstrap_state
from elasticsearch_tpu.cluster.state import ShardRoutingEntry
from elasticsearch_tpu.testing.deterministic import (
    DeterministicTaskQueue, DisruptableTransport,
)
from elasticsearch_tpu.testing.faults import (
    FaultInjectingTransport, FaultRule,
)

DIMS = 4


class FaultyCluster:
    """TestCluster (test_multi_node) + the fault-injection wrapper.

    backend="sim": one shared DisruptableTransport on the deterministic
    task queue (virtual time). backend="tcp": one TcpTransportService
    per node on a real event loop (wall time), each wrapped in its own
    FaultInjectingTransport — the wrappers SHARE one rule set / killed
    set / stats dict, so `c.faults.inject(...)` and `kill_node` govern
    the whole cluster exactly as the sim's single shared wrapper does.
    """

    def __init__(self, tmp_path, n_nodes=3, seed=0, with_faults=True,
                 backend="sim"):
        self.backend = backend
        ids = [f"n{i}" for i in range(n_nodes)]
        initial = bootstrap_state(ids)
        self.nodes = {}
        if backend == "sim":
            self.queue = DeterministicTaskQueue(seed=seed)
            inner = DisruptableTransport(self.queue)
            if with_faults:
                self.faults = FaultInjectingTransport(inner,
                                                      scheduler=self.queue)
                self.transport = self.faults
            else:
                self.faults = None
                self.transport = inner
            for nid in ids:
                self.nodes[nid] = ClusterNode(
                    nid, str(tmp_path / nid), self.transport, self.queue,
                    seed_peers=[p for p in ids if p != nid],
                    initial_state=initial)
        else:
            from elasticsearch_tpu.transport.tcp import (
                AsyncioScheduler, TcpTransportService)
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            self._tcp_inners = {nid: TcpTransportService(nid, loop=self.loop)
                                for nid in ids}
            self.loop.run_until_complete(asyncio.gather(
                *[t.bind() for t in self._tcp_inners.values()]))
            for nid, t in self._tcp_inners.items():
                for other, ot in self._tcp_inners.items():
                    if other != nid:
                        t.add_peer_address(other, *ot.bound_address)
            self.faults = None
            for i, nid in enumerate(ids):
                sched = AsyncioScheduler(self.loop, seed=seed + i)
                transport = self._tcp_inners[nid]
                if with_faults:
                    wrapper = FaultInjectingTransport(transport,
                                                      scheduler=sched)
                    if self.faults is None:
                        self.faults = wrapper
                    else:
                        wrapper.rules = self.faults.rules
                        wrapper._killed = self.faults._killed
                        wrapper.stats = self.faults.stats
                    transport = wrapper
                self.nodes[nid] = ClusterNode(
                    nid, str(tmp_path / nid), transport, sched,
                    seed_peers=[p for p in ids if p != nid],
                    initial_state=initial)
        for n in self.nodes.values():
            n.start()

    def now_ms(self):
        if self.backend == "sim":
            return self.queue.now_ms
        return self.loop.time() * 1000.0

    def run_until(self, cond, max_ms=120_000, step=200):
        if self.backend == "sim":
            waited = 0
            while waited < max_ms:
                self.queue.run_for(step)
                waited += step
                if cond():
                    return True
            return cond()
        deadline = self.loop.time() + min(max_ms, 60_000) / 1000.0
        while self.loop.time() < deadline:
            self.loop.run_until_complete(asyncio.sleep(0.02))
            if cond():
                return True
        return cond()

    def master(self):
        for n in self.nodes.values():
            if n.is_master and not n.coordinator.stopped:
                return n
        return None

    def all_started(self, index):
        n = next(iter(self.nodes.values()))
        shards = n.cluster_state.shards_of(index)
        return bool(shards) and all(
            s.state == ShardRoutingEntry.STARTED for s in shards)

    def call(self, fn, *args, **kw):
        box = {}
        fn(*args, **kw, on_done=lambda r: box.update(r=r))
        ok = self.run_until(lambda: "r" in box)
        assert ok, f"no response from {fn.__name__}"
        return box["r"]

    def stop(self):
        for n in self.nodes.values():
            if not n.coordinator.stopped:
                n.stop()
        if self.backend == "tcp":
            self.loop.run_until_complete(asyncio.gather(
                *[t.close() for t in self._tcp_inners.values()]))
            self.loop.close()


def _rng(seed=7):
    return np.random.default_rng(seed)


def _build(c, index="docs", shards=3, docs=30, vectors=True):
    """Create a replicas=0 index spread over the cluster and load it."""
    mappings = {"properties": {"title": {"type": "text"},
                               "n": {"type": "long"}}}
    if vectors:
        mappings["properties"]["v"] = {"type": "dense_vector",
                                       "dims": DIMS}
    coord = c.nodes["n0"]
    assert c.call(coord.client_create_index, index,
                  settings={"index.number_of_shards": shards,
                            "index.number_of_replicas": 0},
                  mappings=mappings).get("acknowledged")
    assert c.run_until(lambda: c.all_started(index)), "shards not started"
    rng = _rng()
    for i in range(docs):
        src = {"title": f"doc {i}", "n": i}
        if vectors:
            src["v"] = rng.standard_normal(DIMS).astype(float).tolist()
        r = c.call(coord.client_write, index,
                   {"type": "index", "id": f"d{i}", "source": src})
        assert r.get("result") in ("created", "updated"), r
    c.call(coord.client_refresh, index)
    return coord


def _victim(c, index, coordinator_id="n0"):
    """A node other than the coordinator holding >=1 STARTED shard."""
    state = c.nodes[coordinator_id].cluster_state
    held = {}
    for r in state.routing:
        if r.index == index and r.state == ShardRoutingEntry.STARTED:
            held.setdefault(r.node_id, []).append(r.shard)
    for nid in sorted(held):
        if nid != coordinator_id:
            return nid, held[nid]
    raise AssertionError(f"no remote shard holder: {held}")


@pytest.fixture
def cluster(tmp_path):
    c = FaultyCluster(tmp_path, n_nodes=3, seed=17)

    def stable():
        m = c.master()
        return m is not None and len(m.cluster_state.nodes) == 3

    assert c.run_until(stable), "cluster did not stabilize"
    yield c
    c.stop()


@pytest.fixture(params=["sim", "tcp"])
def wire_cluster(tmp_path, request):
    """The core fault scenarios run on BOTH transports: deterministic
    simulator and real asyncio TCP sockets."""
    c = FaultyCluster(tmp_path, n_nodes=3, seed=17,
                      backend=request.param)

    def stable():
        m = c.master()
        return m is not None and len(m.cluster_state.nodes) == 3

    assert c.run_until(stable), f"{request.param} cluster did not stabilize"
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# expired budget → partial results
# ---------------------------------------------------------------------------

def test_expired_budget_returns_partial_with_shard_accounting(wire_cluster):
    c = wire_cluster
    coord = _build(c, vectors=False)
    victim, victim_shards = _victim(c, "docs")
    # tight phase budget so the per-shard timers fire fast
    assert c.call(coord.client_update_settings,
                  {"search.fanout.query_budget_ms": 400,
                   "search.fanout.fetch_budget_ms": 400,
                   "search.fanout.deadline_grace_ms": 50}
                  ).get("acknowledged")
    # the victim's query phase goes silent: requests vanish (the silent-
    # partition shape — no response, no failure)
    c.faults.inject(FaultRule(target=victim, action=QUERY_SHARD,
                              drop=True))
    t0 = c.now_ms()
    resp = c.call(coord.client_search, "docs",
                  {"query": {"match_all": {}}, "size": 30})
    assert resp["timed_out"] is True
    assert resp["_shards"]["total"] == 3
    assert resp["_shards"]["failed"] == len(victim_shards)
    assert resp["_shards"]["successful"] == 3 - len(victim_shards)
    assert resp["_shards"]["skipped"] == 0
    # hits from the surviving shards are served, and the partial fan-in's
    # total is a lower bound
    assert len(resp["hits"]["hits"]) > 0
    assert resp["hits"]["total"]["relation"] == "gte"
    # the response arrived via the budget timer, not a hang: bounded by
    # budget + scheduler slack (virtual OR wall-clock ms, per backend)
    assert c.now_ms() - t0 < 5_000
    phase = coord.fanout_stats.phases["query"]
    assert phase["timed_out"] == len(victim_shards)
    assert coord.fanout_stats.partial_responses >= 1
    # per-node slow tally feeds the ARS observer: the victim must now
    # rank behind nodes that answered
    assert coord.fanout_stats.per_node[victim]["slow"] >= 1
    assert coord._ars_ewma[victim] >= max(
        v for k, v in coord._ars_ewma.items() if k != victim)


def test_partial_results_disallowed_is_an_error(cluster):
    c = cluster
    coord = _build(c, vectors=False)
    victim, _ = _victim(c, "docs")
    assert c.call(coord.client_update_settings,
                  {"search.fanout.query_budget_ms": 300}
                  ).get("acknowledged")
    c.faults.inject(FaultRule(target=victim, action=QUERY_SHARD,
                              drop=True))
    resp = c.call(coord.client_search, "docs",
                  {"query": {"match_all": {}},
                   "allow_partial_search_results": False})
    assert resp.get("status") == 503
    assert resp["error"]["type"] == "search_phase_execution_exception"


# ---------------------------------------------------------------------------
# dead node → no hang, failure counted
# ---------------------------------------------------------------------------

def test_dead_node_fanout_completes_with_failures(wire_cluster):
    c = wire_cluster
    coord = _build(c, vectors=False)
    victim, victim_shards = _victim(c, "docs")
    assert c.call(coord.client_update_settings,
                  {"search.fanout.query_budget_ms": 500}
                  ).get("acknowledged")
    c.faults.kill_node(victim)
    resp = c.call(coord.client_search, "docs",
                  {"query": {"match_all": {}}, "size": 30})
    assert resp["timed_out"] is True
    assert resp["_shards"]["failed"] == len(victim_shards)
    assert len(resp["hits"]["hits"]) > 0
    assert c.faults.stats["dropped"] > 0
    # a second search still answers (the path stays healthy under the
    # sustained fault; ARS now deprioritizes the dead node's copies)
    resp2 = c.call(coord.client_search, "docs",
                   {"query": {"match_all": {}}, "size": 5})
    assert resp2["_shards"]["failed"] >= 1


def test_all_copies_red_early_return_matches_response_contract(cluster):
    c = cluster
    _build(c, index="solo", shards=1, docs=3, vectors=False)
    state = c.nodes["n0"].cluster_state
    victim = next(r.node_id for r in state.shards_of("solo")
                  if r.state == ShardRoutingEntry.STARTED)
    coord = c.nodes[[n for n in c.nodes if n != victim][0]]
    c.faults.kill_node(victim)
    c.nodes[victim].stop()
    # wait until the master evicts the dead node and the shard goes red
    assert c.run_until(lambda: not any(
        r.state == ShardRoutingEntry.STARTED and r.node_id
        for r in coord.cluster_state.shards_of("solo")), max_ms=300_000)
    resp = c.call(coord.client_search, "solo",
                  {"query": {"match_all": {}}})
    # the normalized contract: same shape as every other search response
    assert resp["timed_out"] is False
    assert resp["took"] >= 0
    assert resp["_shards"] == {"total": 1, "successful": 0,
                               "skipped": 0, "failed": 1}
    assert resp["hits"] == {"total": {"value": 0, "relation": "eq"},
                            "max_score": None, "hits": []}


# ---------------------------------------------------------------------------
# slow node → remote shed via the continuous batcher's EDF queue
# ---------------------------------------------------------------------------

def test_slow_node_sheds_at_remote_batcher_not_coordinator_timer(wire_cluster):
    c = wire_cluster
    coord = _build(c, vectors=True)
    victim, victim_shards = _victim(c, "docs")
    # deliver the victim's QUERY sub-requests 500ms late — past the
    # request's 200ms deadline, but well inside the coordinator's
    # (budget + grace) backstop
    c.faults.inject(FaultRule(target=victim, action=QUERY_SHARD,
                              delay_ms=500))
    body = {"knn": {"field": "v",
                    "query_vector": _rng(3).standard_normal(
                        DIMS).astype(float).tolist(),
                    "k": 5, "num_candidates": 5},
            "size": 5, "timeout": "200ms"}
    resp = c.call(coord.client_search, "docs", body)
    assert resp["timed_out"] is True
    assert resp["_shards"]["failed"] == len(victim_shards)
    assert len(resp["hits"]["hits"]) > 0

    # THE deadline-propagation proof: the remote's continuous batcher
    # shed the sub-request on the propagated absolute deadline (EDF
    # schedule-time shed), and the coordinator merely attributed it —
    # its own backstop timer never fired for the query phase.
    vnode = c.nodes[victim]
    assert vnode.fanout_stats.remote["sheds_batcher"] >= 1
    shard_sheds = sum(
        sh.vector_store.scheduler_stats().get("deadline_sheds", 0)
        for sh in vnode.local_shards.values())
    assert shard_sheds >= 1, \
        "the shed must come from the batcher's EDF queue"
    phase = coord.fanout_stats.phases["query"]
    assert phase["shed"] == len(victim_shards)
    assert phase["timed_out"] == 0, \
        "coordinator backstop must not fire when the remote sheds itself"


def test_expired_pure_host_subrequest_sheds_at_admission(cluster):
    c = cluster
    coord = _build(c, vectors=False)
    victim, victim_shards = _victim(c, "docs")
    c.faults.inject(FaultRule(target=victim, action=QUERY_SHARD,
                              delay_ms=500))
    resp = c.call(coord.client_search, "docs",
                  {"query": {"match_all": {}}, "timeout": "150ms",
                   "size": 30})
    assert resp["timed_out"] is True
    assert c.nodes[victim].fanout_stats.remote["sheds_admission"] >= 1
    assert coord.fanout_stats.phases["query"]["shed"] == \
        len(victim_shards)


# ---------------------------------------------------------------------------
# parity: the harness at zero faults is invisible
# ---------------------------------------------------------------------------

def _strip_took(resp):
    out = dict(resp)
    out.pop("took", None)
    # timing surface like `took`: the coordinator's phase summary carries
    # virtual elapsed_ms, not accumulator behavior
    out.pop("_took_phases", None)
    return out


def test_accumulator_parity_with_no_fault_path(tmp_path):
    responses = []
    for with_faults in (True, False):
        c = FaultyCluster(tmp_path / f"w{int(with_faults)}", n_nodes=3,
                          seed=17, with_faults=with_faults)
        assert c.run_until(lambda: c.master() is not None
                           and len(c.master().cluster_state.nodes) == 3)
        coord = _build(c)
        body = {"query": {"match": {"title": "doc"}},
                "knn": {"field": "v",
                        "query_vector": _rng(5).standard_normal(
                            DIMS).astype(float).tolist(),
                        "k": 4, "num_candidates": 4},
                "size": 10,
                "aggs": {"m": {"max": {"field": "n"}}}}
        responses.append(_strip_took(c.call(coord.client_search,
                                            "docs", body)))
        c.stop()
    assert responses[0] == responses[1]


def test_knn_response_byte_parity_sim_vs_sockets(tmp_path):
    """The same kNN+match+aggs search against the same corpus must
    produce a byte-identical JSON response whether the cluster runs on
    the in-process simulator or over real TCP sockets — serialization
    through the wire must not perturb scores, ordering, or shapes
    (modulo timing fields, which are stripped)."""
    payloads = []
    for backend in ("sim", "tcp"):
        c = FaultyCluster(tmp_path / backend, n_nodes=3, seed=17,
                          with_faults=False, backend=backend)
        assert c.run_until(lambda: c.master() is not None
                           and len(c.master().cluster_state.nodes) == 3)
        coord = _build(c)
        body = {"query": {"match": {"title": "doc"}},
                "knn": {"field": "v",
                        "query_vector": _rng(5).standard_normal(
                            DIMS).astype(float).tolist(),
                        "k": 4, "num_candidates": 4},
                "size": 10,
                "aggs": {"m": {"max": {"field": "n"}}}}
        resp = _strip_took(c.call(coord.client_search, "docs", body))
        payloads.append(json.dumps(resp, sort_keys=True).encode())
        c.stop()
    assert payloads[0] == payloads[1]


# ---------------------------------------------------------------------------
# observability: profile.fanout + stats snapshot shape
# ---------------------------------------------------------------------------

def test_profile_fanout_section_and_stats_snapshot(cluster):
    c = cluster
    coord = _build(c, vectors=False)
    resp = c.call(coord.client_search, "docs",
                  {"query": {"match_all": {}}, "profile": True})
    prof = resp["profile"]["fanout"]
    assert prof["query"]["targets"] == 3
    assert prof["query"]["ok"] == 3
    assert prof["query"]["timed_out"] is False
    assert "fetch" in prof
    snap = coord.fanout_stats.snapshot()
    assert snap["phases"]["query"]["launched"] >= 3
    assert "per_node" in snap and "remote" in snap
    assert set(snap["remote"]) == {"sheds_admission", "sheds_batcher"}
