"""Device-resident aggregations (ops/aggs.py + search/agg_plan.py).

Two contracts, mirroring the mesh-serving suite's shape:

1. PARITY — the device path is numerically IDENTICAL (json-equal) to the
   host walkers for every supported agg, in both final mode
   (`compute_aggs`) and distributed-partial mode
   (`compute_partial_aggs` → `merge_partial_aggs` → `finalize_aggs`),
   including one-level sub-aggs, `missing`, empty match sets, host
   fallbacks, and the SPMD mesh path on ragged shards.

2. CLOSED GRID — steady-state device aggs compile nothing: warmed second
   passes run under strict dispatch with a zero compile delta (the
   `aggs.*` grid rides the standalone ES_TPU_DISPATCH_STRICT=1
   recompile-regression gate through the multidevice-marked tests).
"""

import json
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.search.agg_partials import (
    compute_partial_aggs, finalize_aggs, merge_partial_aggs,
)
from elasticsearch_tpu.search.agg_plan import AggEngine
from elasticsearch_tpu.search.aggregations import compute_aggs
from elasticsearch_tpu.search.queries import SearchContext

MAPPING = {"properties": {
    "cat": {"type": "keyword"},
    "tags": {"type": "keyword"},
    "v": {"type": "long"},
    "nums": {"type": "long"},
    "price": {"type": "double"},
    "flag": {"type": "boolean"},
    "ts": {"type": "date"},
}}


def _index_docs(e, n=240):
    for i in range(n):
        doc = {"cat": ["red", "green", "blue", "teal"][i % 4],
               "tags": ["a", "b"] if i % 5 == 0 else "c",
               "v": i,
               "nums": [i, i + 1000] if i % 4 == 0 else i,
               "flag": i % 2 == 0,
               "ts": 1_600_000_000_000 + (i % 6) * 3_600_000}
        if i % 7 != 0:
            doc["price"] = i * 0.5
        if i % 11 == 0:
            del doc["cat"]
        e.index(str(i), doc)
    e.refresh()


@pytest.fixture(scope="module")
def ctx():
    e = Engine(tempfile.mkdtemp() + "/shard", MapperService(MAPPING))
    _index_docs(e)
    yield SearchContext(e.acquire_searcher(), e.mapper_service)
    e.close()


@pytest.fixture()
def engine(ctx):
    return AggEngine(ctx.mapper_service)


def _rows(ctx, frac=3):
    rows = ctx.all_rows()
    return rows[rows % frac != 0]


def _json(x):
    return json.dumps(x, sort_keys=True, default=str)


DEVICE_SPECS = [
    # terms: keyword / numeric / boolean / missing / mdc 0 / order / size
    {"t": {"terms": {"field": "cat"}}},
    {"t": {"terms": {"field": "cat", "size": 2}}},
    {"t": {"terms": {"field": "cat", "missing": "none"}}},
    {"t": {"terms": {"field": "cat", "min_doc_count": 0,
                     "order": {"_key": "desc"}}}},
    {"t": {"terms": {"field": "cat", "order": {"_count": "asc"}}}},
    {"t": {"terms": {"field": "v", "size": 5}}},
    {"t": {"terms": {"field": "flag"}}},
    {"t": {"terms": {"field": "ts", "size": 3}}},
    # terms + one-level sub metrics (incl. missing bucket sub-aggs)
    {"t": {"terms": {"field": "cat", "missing": "other"},
           "aggs": {"s": {"stats": {"field": "v"}},
                    "c": {"value_count": {"field": "v"}},
                    "mx": {"max": {"field": "price"}}}}},
    # histogram: offset / missing / min_doc_count 0 / extended_bounds /
    # format / sub-aggs
    {"h": {"histogram": {"field": "v", "interval": 25, "offset": 3}}},
    {"h": {"histogram": {"field": "v", "interval": 25, "missing": 7,
                         "min_doc_count": 0}}},
    {"h": {"histogram": {"field": "v", "interval": 10,
                         "extended_bounds": {"min": -50, "max": 300}},
           "aggs": {"a": {"avg": {"field": "v"}}}}},
    {"h": {"histogram": {"field": "v", "interval": 50,
                         "format": "0.0"}}},
    # date_histogram: fixed intervals, format, offset, sub-aggs
    {"d": {"date_histogram": {"field": "ts", "fixed_interval": "1h"}}},
    {"d": {"date_histogram": {"field": "ts", "fixed_interval": "2h",
                              "offset": "+30m",
                              "format": "yyyy-MM-dd HH:mm"},
           "aggs": {"mn": {"min": {"field": "v"}}}}},
    # calendar intervals ride the boundary-table kernel (rung 2)
    {"d": {"date_histogram": {"field": "ts",
                              "calendar_interval": "hour"}}},
    # cardinality: device HLL boards (rung 2)
    {"c": {"cardinality": {"field": "cat"}}},
    {"t": {"terms": {"field": "cat"},
           "aggs": {"cd": {"cardinality": {"field": "v"}}}}},
    # 2-level sub-agg tree: composite-id boards (rung 2)
    {"t": {"terms": {"field": "cat"},
           "aggs": {"by_flag": {"terms": {"field": "flag"},
                                "aggs": {"s": {"stats": {"field":
                                                         "v"}}}}}}},
    # range: open ends / keys / overlaps / sub-aggs
    {"r": {"range": {"field": "v",
                     "ranges": [{"to": 50}, {"from": 50, "to": 150,
                                             "key": "mid"},
                                {"from": 100}]},
           "aggs": {"s": {"sum": {"field": "v"}}}}},
    # top-level metrics (integral sums; min/max on floats; date avg)
    {"m": {"avg": {"field": "v"}}},
    {"m": {"sum": {"field": "v"}}, "m2": {"stats": {"field": "v",
                                                    "missing": 7}}},
    {"m": {"min": {"field": "price"}}, "m2": {"max": {"field": "price"}}},
    {"m": {"value_count": {"field": "v"}}},
    {"m": {"avg": {"field": "ts"}}},
    # meta + pipeline over a device sibling
    {"t": {"terms": {"field": "cat"}, "meta": {"who": "dash"}},
     "p": {"max_bucket": {"buckets_path": "t>_count"}}},
]

FALLBACK_SPECS = [
    # every node host-side, but responses must still be identical
    {"m": {"sum": {"field": "price"}}},                    # non-integral
    {"m": {"value_count": {"field": "cat"}}},              # keyword count
    # value_count counts every VALUE of a multi-valued field while the
    # f64 column holds only the first — must route host (other metrics
    # use first-value semantics on both paths and stay device-eligible)
    {"m": {"value_count": {"field": "nums"}}},
    {"t": {"terms": {"field": "cat"},
           "aggs": {"c": {"value_count": {"field": "nums"}}}}},
    {"t": {"terms": {"field": "tags"}}},                   # multi-valued
    {"c": {"cardinality": {"field": "tags"}}},             # multi-valued HLL
    {"t": {"terms": {"field": "cat", "include": ["red", "blue"]}}},
]


@pytest.mark.parametrize("spec", DEVICE_SPECS)
def test_device_final_parity(ctx, engine, spec):
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    assert got is not None, "expected a device-eligible plan"
    dev, prof = got
    assert _json(dev) == _json(host)
    assert any(n["engine"].startswith("device") for n in prof["nodes"])


@pytest.mark.parametrize("spec", FALLBACK_SPECS)
def test_host_fallback_parity(ctx, engine, spec):
    rows = _rows(ctx)
    host = compute_aggs(ctx, rows, spec)
    got = engine.compute(ctx, rows, spec, partial=False)
    if got is None:
        return  # no device-eligible node: caller keeps the host path
    dev, prof = got
    assert _json(dev) == _json(host)


def test_empty_match_set_parity(ctx, engine):
    rows = np.zeros(0, dtype=np.int64)
    for spec in DEVICE_SPECS[:8]:
        host = compute_aggs(ctx, rows, spec)
        got = engine.compute(ctx, rows, spec, partial=False)
        assert got is not None
        assert _json(got[0]) == _json(host)


def test_partial_mode_skewed_reduce_parity(ctx, engine):
    rows = ctx.all_rows()
    n = len(rows)
    splits = [rows[: n // 6], rows[n // 6: n // 2], rows[n // 2:]]
    for spec in DEVICE_SPECS:
        if any("meta" in s or any(k in ("max_bucket",) for k in s)
               for s in spec.values() if isinstance(s, dict)):
            continue  # pipelines defer to finalize in partial mode
        hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
        hm = hp[0]
        for p in hp[1:]:
            hm = merge_partial_aggs(hm, p, spec)
        host = finalize_aggs(hm, spec)
        dp = []
        for r in splits:
            got = engine.compute(ctx, r, spec, partial=True)
            assert got is not None
            dp.append(got[0])
        dm = dp[0]
        for p in dp[1:]:
            dm = merge_partial_aggs(dm, p, spec)
        assert _json(finalize_aggs(dm, spec)) == _json(host)


def test_plan_cache_hits_on_repeated_dashboard_body(ctx, engine):
    rows = _rows(ctx)
    body = {"h": {"histogram": {"field": "v", "interval": 25}},
            "t": {"terms": {"field": "cat"},
                  "aggs": {"s": {"stats": {"field": "v"}}}}}
    engine.compute(ctx, rows, body, partial=False)
    # a dashboard slider: interval changes are scrubbed from the plan key
    body2 = json.loads(json.dumps(body))
    body2["h"]["histogram"]["interval"] = 50
    engine.compute(ctx, rows, body2, partial=False)
    assert engine.stats["plan_cache_hits"] >= 1
    # parity still holds for the re-bound plan
    host = compute_aggs(ctx, rows, body2)
    assert _json(engine.compute(ctx, rows, body2)[0]) == _json(host)


def test_strict_zero_recompile_second_pass(ctx, engine):
    rows = _rows(ctx)
    spec = {"t": {"terms": {"field": "cat"},
                  "aggs": {"s": {"stats": {"field": "v"}}}},
            "h": {"histogram": {"field": "v", "interval": 25}},
            "r": {"range": {"field": "v", "ranges": [{"to": 100},
                                                     {"from": 100}]}},
            "m": {"avg": {"field": "v"}}}
    engine.compute(ctx, rows, spec, partial=False)  # warm pass
    before = dispatch.DISPATCH.compile_count()
    strict_before = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    try:
        got = engine.compute(ctx, rows, spec, partial=False)
    finally:
        dispatch.DISPATCH.strict = strict_before
    assert got is not None
    assert dispatch.DISPATCH.compile_count() == before


def test_warmup_entries_precompile_column_grid(ctx, engine):
    rows = _rows(ctx)
    col = engine.store.column(ctx.reader, "v")
    entries = engine.store.warmup_entries(col)
    assert entries
    dispatch.DISPATCH.warmup(entries, background=False)
    # the warmed shapes are the ones real dispatches hit: a fresh metric
    # agg with B on the warmup ladder must not compile
    before = dispatch.DISPATCH.compile_count()
    got = engine.compute(ctx, rows, {"m": {"sum": {"field": "v"}}})
    assert got is not None
    assert dispatch.DISPATCH.compile_count() == before


def test_columnar_host_fast_path_matches_loop(ctx):
    """Satellite: the vectorized numeric_values/all_values fast path is
    value-identical to the per-row get_doc_value loop it replaced."""
    from elasticsearch_tpu.search import aggregations as A
    rows = _rows(ctx)

    def legacy_numeric(field, missing=None):
        f = ctx.mapper_service.resolve_field(field)
        vals = np.full(len(rows), np.nan, dtype=np.float64)
        present = np.zeros(len(rows), dtype=bool)
        for i, row in enumerate(rows):
            v = ctx.reader.get_doc_value(f, int(row))
            if isinstance(v, list):
                v = v[0] if v else None
            if v is None:
                continue
            if isinstance(v, bool):
                v = 1.0 if v else 0.0
            if isinstance(v, (int, float)):
                vals[i] = float(v)
                present[i] = True
        if missing is not None:
            vals[~present] = missing
            present[:] = True
        return vals, present

    for field in ("v", "price", "ts"):
        fast_v, fast_p = A.numeric_values(ctx, rows, field)
        ref_v, ref_p = legacy_numeric(field)
        assert np.array_equal(fast_p, ref_p)
        assert np.array_equal(fast_v[fast_p], ref_v[ref_p])
    fv, fp = A.numeric_values(ctx, rows, "price", missing=-1.0)
    rv, rp = legacy_numeric("price", missing=-1.0)
    assert np.array_equal(fv, rv) and fp.all()

    def legacy_all(field):
        f = ctx.mapper_service.resolve_field(field)
        out = []
        for i, row in enumerate(rows):
            v = ctx.reader.get_doc_value(f, int(row))
            if v is None:
                continue
            if isinstance(v, list):
                out.extend((i, item) for item in v if item is not None)
            else:
                out.append((i, v))
        return out

    for field in ("cat", "tags", "v"):
        assert A.all_values(ctx, rows, field) == legacy_all(field)


# ---------------------------------------------------------------------------
# SPMD mesh path (the 8 virtual CPU devices conftest forces)
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
class TestMeshAggs:
    def _mk(self, n=900):
        e = Engine(tempfile.mkdtemp() + "/shard", MapperService(MAPPING))
        _index_docs(e, n=n)  # 900 live rows -> 1024 row bucket: ragged
        ctx = SearchContext(e.acquire_searcher(), e.mapper_service)
        return e, ctx

    def test_mesh_parity_ragged_shards(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = _rows(ctx)
            for spec in (
                    {"t": {"terms": {"field": "cat"},
                           "aggs": {"s": {"stats": {"field": "v"}}}}},
                    {"h": {"histogram": {"field": "v", "interval": 100,
                                         "min_doc_count": 0}}},
                    {"d": {"date_histogram": {"field": "ts",
                                              "fixed_interval": "2h"}}},
                    {"r": {"range": {"field": "v",
                                     "ranges": [{"to": 400},
                                                {"from": 400}]},
                           "aggs": {"m": {"min": {"field": "v"}}}}},
                    {"m": {"avg": {"field": "v"}}}):
                host = compute_aggs(ctx, rows, spec)
                got = engine.compute(ctx, rows, spec, partial=False)
                assert got is not None
                assert _json(got[0]) == _json(host)
            st = mesh_serving.stats()
            assert st["legs"].get("aggs", {}).get("dispatches", 0) > 0
            assert engine.stats["mesh_dispatches"] > 0
        finally:
            e.close()

    def test_mesh_empty_and_full_masks(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            for rows in (np.zeros(0, dtype=np.int64), ctx.all_rows()):
                spec = {"t": {"terms": {"field": "cat"}},
                        "m": {"stats": {"field": "v"}}}
                host = compute_aggs(ctx, rows, spec)
                got = engine.compute(ctx, rows, spec, partial=False)
                assert got is not None
                assert _json(got[0]) == _json(host)
        finally:
            e.close()

    def test_mesh_strict_zero_recompile_second_pass(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = _rows(ctx)
            spec = {"t": {"terms": {"field": "cat"},
                          "aggs": {"s": {"stats": {"field": "v"}}}},
                    "h": {"histogram": {"field": "v", "interval": 100}}}
            engine.compute(ctx, rows, spec, partial=False)  # warm
            before = dispatch.DISPATCH.compile_count()
            strict_before = dispatch.DISPATCH.strict
            dispatch.DISPATCH.strict = True
            try:
                got = engine.compute(ctx, rows, spec, partial=False)
            finally:
                dispatch.DISPATCH.strict = strict_before
            assert got is not None
            assert dispatch.DISPATCH.compile_count() == before
        finally:
            e.close()

    def test_mesh_partial_states_merge_like_host(self, mesh_serving):
        e, ctx = self._mk()
        try:
            engine = AggEngine(ctx.mapper_service)
            rows = ctx.all_rows()
            splits = [rows[:100], rows[100:600], rows[600:]]
            spec = {"t": {"terms": {"field": "cat"},
                          "aggs": {"a": {"avg": {"field": "v"}}}}}
            hp = [compute_partial_aggs(ctx, r, spec) for r in splits]
            hm = hp[0]
            for p in hp[1:]:
                hm = merge_partial_aggs(hm, p, spec)
            dp = [engine.compute(ctx, r, spec, partial=True)[0]
                  for r in splits]
            dm = dp[0]
            for p in dp[1:]:
                dm = merge_partial_aggs(dm, p, spec)
            assert _json(finalize_aggs(dm, spec)) == \
                _json(finalize_aggs(hm, spec))
        finally:
            e.close()


# ---------------------------------------------------------------------------
# node-level wiring: REST-shaped search, settings gate, stats, profile
# ---------------------------------------------------------------------------


def _mk_node(tmp):
    from elasticsearch_tpu.node import Node
    node = Node(tmp)
    # the measured router may (correctly) route this tiny corpus host;
    # these tests pin device-vs-host PARITY, so force the device path
    node.settings["search.aggs.cost_router"] = "false"
    node.create_index_with_templates("logs", mappings={"properties": {
        "cat": {"type": "keyword"}, "v": {"type": "long"},
        "ts": {"type": "date"}}})
    ops = []
    for i in range(400):
        ops.append({"index": {"_index": "logs", "_id": str(i)}})
        ops.append({"cat": ["a", "b", "c"][i % 3], "v": i,
                    "ts": 1_600_000_000_000 + (i % 12) * 3_600_000})
    node.bulk(ops)
    node.indices.get("logs").refresh()
    return node


DASH_BODY = {"query": {"range": {"v": {"gte": 100}}}, "size": 5,
             "aggs": {"by_cat": {"terms": {"field": "cat"},
                                 "aggs": {"s": {"stats": {"field": "v"}}}},
                      "over_time": {"date_histogram": {
                          "field": "ts", "fixed_interval": "2h"}}}}


def test_node_search_device_vs_disabled_parity(tmp_path):
    node = _mk_node(str(tmp_path))
    try:
        body = json.loads(json.dumps(DASH_BODY))
        r1 = node.search("logs", json.loads(json.dumps(body)))
        eng = node._aggs["logs"][1]
        assert eng.stats["device_nodes"] >= 2
        node.settings["search.aggs.device_enabled"] = "false"
        r2 = node.search("logs", json.loads(json.dumps(body)))
        r1.pop("took"), r2.pop("took")
        assert _json(r1) == _json(r2)
        # stats + profile sections
        node.settings.pop("search.aggs.device_enabled")
        st = node.local_node_stats()["indices"]["aggs"]
        assert st["device_nodes"] >= 2 and st["columns"] >= 2
        body["profile"] = True
        rp = node.search("logs", json.loads(json.dumps(body)))
        entries = rp["profile"]["shards"][0]["aggregations"]
        assert {a["description"]: a.get("engine") for a in entries} == {
            "by_cat": "device", "over_time": "device"}
    finally:
        node.close()


def test_node_multi_index_partial_aggs_parity(tmp_path):
    """Multi-index searches ship partial states; device partials must
    reduce to the same response as host partials."""
    from elasticsearch_tpu.node import Node
    node = Node(str(tmp_path))
    try:
        for idx in ("logs1", "logs2"):
            node.create_index_with_templates(idx, mappings={"properties": {
                "cat": {"type": "keyword"}, "v": {"type": "long"}}})
        ops = []
        for i in range(300):
            ops.append({"index": {"_index": "logs1" if i % 2 else "logs2",
                                  "_id": str(i)}})
            ops.append({"cat": ["a", "b", "c"][i % 3], "v": i})
        node.bulk(ops)
        for idx in ("logs1", "logs2"):
            node.indices.get(idx).refresh()
        body = {"size": 3, "aggs": {
            "by_cat": {"terms": {"field": "cat"},
                       "aggs": {"a": {"avg": {"field": "v"}}}},
            "vs": {"stats": {"field": "v"}}}}
        r1 = node.search("logs1,logs2", json.loads(json.dumps(body)))
        node.settings["search.aggs.device_enabled"] = "false"
        r2 = node.search("logs1,logs2", json.loads(json.dumps(body)))
        r1.pop("took"), r2.pop("took")
        assert _json(r1) == _json(r2)
    finally:
        node.close()
