// Native hot-loop kernels for the host-side lexical search path.
//
// The reference leans on Lucene's C-like Java hot loops for postings
// iteration, BM25 scoring, and top-k heaps (SURVEY.md §2.9: "the TPU build
// ... needs a C++ implementation wherever the reference relies on Lucene's
// hot loops: postings decode, BM25 scoring, top-k heaps"). Vector scoring
// runs on the TPU (ops/, parallel/); these kernels cover the scalar,
// branchy, host-side loops where neither numpy vectorization nor XLA is the
// right tool: galloping sorted-set intersection (bool MUST), k-way
// union-with-score-sum (bool SHOULD), fused BM25 term scoring
// (queries.py bm25_scores), and partial top-k selection
// (search/service.py result ranking).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Every function is allocation-free: callers pass numpy-owned buffers.

#include <algorithm>
#include <thread>
#include <vector>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// Host-side int8 kNN (the latency serving path).
//
// A TPU dispatch costs a fixed host<->device round trip; for corpora small
// enough that one CPU pass beats that overhead, the serving layer routes
// searches here instead (serving/batcher.py's cost model). The reference has
// no analog -- Lucene scores vectors per-doc in Java (ScoreScriptUtils.java);
// this kernel is a cache-blocked u8*i8 GEMM + per-query top-k heap, using
// AVX512-VNNI (vpdpbusd) when the host has it.
//
// Layout: the corpus is PRE-PACKED into 16-row groups, interleaved so one
// 64-byte load covers 4 dims x 16 rows: pack[g][j][row 0..15][4 dims], with
// j in [0, d4), d4 = row_stride/4 -- stored u8 with a +128 offset so the
// corpus sits in vpdpbusd's UNSIGNED operand. Queries are quantized i8 and
// stay compact ([16][d4*4], L1-resident); each inner step feeds vpdpbusd an
// EMBEDDED 4-byte broadcast of the query (m32{1to16}), so the loop is one
// 64B corpus load + 16 broadcast-fused vpdpbusd over 16 register
// accumulators -- port-throughput bound, and the only streamed operand is
// the corpus itself.
//
//   dot(q, row) ~ qscale * rscale * (sum(qi8 * (r+128)u8) - 128 * sum(qi8))
//   score       = dot_mul * dot + row_bias[row]
//
// (the +128 correction is per-QUERY, a scalar hoisted out of the row loop;
// cosine/dot: dot_mul 1, bias null; l2: dot_mul 2, bias = -||row||^2.)
// Per-row metadata arrays are padded to ng*16 entries by the caller.

struct TopK {
    // (score desc, row asc) -- same tie-break as es_topk_f32 below
    float* s;
    int32_t* r;
    int64_t k, size;
    inline bool better(float xs, int32_t xr, float ys, int32_t yr) const {
        if (xs != ys) return xs > ys;
        return xr < yr;
    }
    inline void sift_up(int64_t i) {
        while (i > 0) {
            int64_t p = (i - 1) >> 1;
            // heap top = worst retained; parent must be <= child
            if (better(s[p], r[p], s[i], r[i])) {
                std::swap(s[p], s[i]);
                std::swap(r[p], r[i]);
                i = p;
            } else break;
        }
    }
    inline void sift_down() {
        int64_t i = 0;
        for (;;) {
            int64_t l = 2 * i + 1, m = i;
            if (l < size && better(s[m], r[m], s[l], r[l])) m = l;
            if (l + 1 < size && better(s[m], r[m], s[l + 1], r[l + 1])) m = l + 1;
            if (m == i) break;
            std::swap(s[m], s[i]);
            std::swap(r[m], r[i]);
            i = m;
        }
    }
    inline void push(float score, int32_t row) {
        if (size < k) {
            s[size] = score; r[size] = row;
            ++size;
            sift_up(size - 1);
        } else if (better(score, row, s[0], r[0])) {
            // full (score desc, row asc) comparison — for the row-ascending
            // scan this equals `score > s[0]`, but the cross-thread merge
            // pushes candidates in heap-array order, where a score tie must
            // still prefer the smaller row or the merged result would
            // depend on the partition
            s[0] = score; r[0] = row;
            sift_down();
        }
    }
};

struct KnnPArgs {
    const float* queries; int64_t b, d;
    const uint8_t* packed; int64_t n, d4;   // u8, +128 offset
    const float* row_scales;   // [ng*16]
    const float* row_bias;     // null or [ng*16]
    float dot_mul;
    const uint8_t* mask;       // null, [ng*16] shared, or [b][mask_stride]
    int64_t mask_stride;       // 0 = shared
    int64_t k;
    float* out_scores;         // [b, k]
    int32_t* out_rows;         // [b, k]
};

// Quantize one query group to compact i8 rows ([qi][d4*4], zero-padded) --
// small enough to stay L1-resident; the VNNI loop broadcasts 4-byte groups
// straight from it via vpdpbusd's embedded-broadcast memory operand.
void quantize_queries_i8(const float* q, int64_t nb, int64_t d, int64_t d4,
                         int8_t* qi8, float* qscales, int32_t* qsums) {
    std::memset(qi8, 0, 16 * d4 * 4);
    for (int64_t qi = 0; qi < 16; ++qi) {
        qscales[qi] = 1.0f;
        qsums[qi] = 0;
        if (qi >= nb) continue;
        const float* row = q + qi * d;
        float amax = 0.0f;
        for (int64_t j = 0; j < d; ++j)
            amax = std::max(amax, std::fabs(row[j]));
        const float scale = amax > 0.0f ? amax / 127.0f : 1.0f;
        qscales[qi] = scale;
        const float inv = 1.0f / scale;
        int32_t sum = 0;
        for (int64_t j = 0; j < d; ++j) {
            int32_t v = static_cast<int32_t>(std::lround(row[j] * inv));
            v = std::min(std::max(v, -127), 127);
            qi8[qi * d4 * 4 + j] = static_cast<int8_t>(v);
            sum += v;
        }
        qsums[qi] = sum;
    }
}

inline void emit_topk(TopK& h, int64_t k, float* os, int32_t* orow) {
    for (int64_t x = 0; x < k; ++x) { os[x] = -INFINITY; orow[x] = -1; }
    while (h.size > 0) {  // pop worst-first into descending positions
        os[h.size - 1] = h.s[0];
        orow[h.size - 1] = h.r[0];
        h.s[0] = h.s[h.size - 1];
        h.r[0] = h.r[h.size - 1];
        --h.size;
        h.sift_down();
    }
}

void knn_i8p_scalar(const KnnPArgs& a) {
    const int64_t ng = (a.n + 15) / 16;
    float* hs = new float[16 * a.k];
    int32_t* hr = new int32_t[16 * a.k];
    int8_t* qi8 = new int8_t[16 * a.d4 * 4];
    for (int64_t q0 = 0; q0 < a.b; q0 += 16) {
        const int64_t nb = std::min<int64_t>(16, a.b - q0);
        float qscales[16];
        int32_t qsums[16];
        quantize_queries_i8(a.queries + q0 * a.d, nb, a.d, a.d4,
                            qi8, qscales, qsums);
        TopK heaps[16];
        for (int64_t qi = 0; qi < nb; ++qi)
            heaps[qi] = TopK{hs + qi * a.k, hr + qi * a.k, a.k, 0};
        for (int64_t g = 0; g < ng; ++g) {
            const int64_t lanes = std::min<int64_t>(16, a.n - g * 16);
            const uint8_t* gp = a.packed + g * a.d4 * 64;
            for (int64_t qi = 0; qi < nb; ++qi) {
                const int8_t* qrow = qi8 + qi * a.d4 * 4;
                const float corr = 128.0f * static_cast<float>(qsums[qi]);
                const float qmul = qscales[qi] * a.dot_mul;
                for (int64_t t = 0; t < lanes; ++t) {
                    const int64_t r = g * 16 + t;
                    if (a.mask) {
                        const uint8_t* mrow = a.mask_stride
                            ? a.mask + (q0 + qi) * a.mask_stride : a.mask;
                        if (!mrow[r]) continue;
                    }
                    int32_t acc = 0;
                    for (int64_t j = 0; j < a.d4; ++j) {
                        const uint8_t* rb = gp + j * 64 + t * 4;
                        for (int64_t u = 0; u < 4; ++u)
                            acc += static_cast<int32_t>(qrow[j * 4 + u]) *
                                   static_cast<int32_t>(rb[u]);
                    }
                    float s = (static_cast<float>(acc) - corr) * qmul;
                    s = s * a.row_scales[r] +
                        (a.row_bias ? a.row_bias[r] : 0.0f);
                    heaps[qi].push(s, static_cast<int32_t>(r));
                }
            }
        }
        for (int64_t qi = 0; qi < nb; ++qi)
            emit_topk(heaps[qi], a.k,
                      a.out_scores + (q0 + qi) * a.k,
                      a.out_rows + (q0 + qi) * a.k);
    }
    delete[] qi8;
    delete[] hs;
    delete[] hr;
}

#if defined(__x86_64__)
// One 16-query x [g_lo, g_hi) row-group scan with private heaps — the unit
// a worker thread executes. Scores are identical however the range is
// partitioned, and TopK's (score desc, row asc) tie-break makes the merged
// result bit-identical to the single-threaded scan.
__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void knn_i8p_vnni_range(const KnnPArgs& a, const int8_t* qi8,
                        const float* qscales, const int32_t* qsums,
                        int64_t q0, int64_t nb,
                        int64_t g_lo, int64_t g_hi, int64_t ng,
                        TopK* heaps, float* heapmin) {
    {
        const bool shared_mask = a.mask && a.mask_stride == 0;
        const int64_t qstride = a.d4 * 4;
        for (int64_t g = g_lo; g < g_hi; ++g) {
            uint16_t gmask = 0xFFFF;
            if (g == ng - 1 && (a.n & 15))
                gmask = static_cast<uint16_t>((1u << (a.n & 15)) - 1);
            if (shared_mask) {
                const __m128i mb = _mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(a.mask + g * 16));
                gmask &= _mm_test_epi8_mask(mb, mb);
                if (!gmask) continue;
            }
            const uint8_t* gp = a.packed + g * a.d4 * 64;
            // named accumulators: an acc ARRAY makes gcc keep it in stack
            // memory, storing every zmm each iteration -- 16 named locals
            // stay in registers (32 zmm available under AVX512). The query
            // operand is a 4-byte embedded broadcast (m32{1to16}) from the
            // compact L1-resident qi8 rows; the only streamed load per step
            // is the 64B corpus line.
#define ES_ACC_EACH(OP) \
    OP(0) OP(1) OP(2) OP(3) OP(4) OP(5) OP(6) OP(7) \
    OP(8) OP(9) OP(10) OP(11) OP(12) OP(13) OP(14) OP(15)
#define ES_ACC_DECL(i) __m512i acc##i = _mm512_setzero_si512();
            ES_ACC_EACH(ES_ACC_DECL)
            for (int64_t j = 0; j < a.d4; ++j) {
                // stream the corpus ~1.5KB ahead: the VM's hardware
                // prefetcher alone leaves the scan demand-miss bound
                _mm_prefetch(reinterpret_cast<const char*>(gp + j * 64 + 1536),
                             _MM_HINT_T0);
                const __m512i rv = _mm512_loadu_si512(gp + j * 64);
                const int8_t* qj = qi8 + j * 4;
                int32_t qw;
#define ES_ACC_DP(i) \
    std::memcpy(&qw, qj + i * qstride, 4); \
    acc##i = _mm512_dpbusd_epi32(acc##i, rv, _mm512_set1_epi32(qw));
                ES_ACC_EACH(ES_ACC_DP)
#undef ES_ACC_DP
            }
            __m512i acc[16];
#define ES_ACC_STORE(i) acc[i] = acc##i;
            ES_ACC_EACH(ES_ACC_STORE)
#undef ES_ACC_STORE
#undef ES_ACC_DECL
#undef ES_ACC_EACH
            const __m512 scales16 = _mm512_loadu_ps(a.row_scales + g * 16);
            const __m512 bias16 = a.row_bias
                ? _mm512_loadu_ps(a.row_bias + g * 16) : _mm512_setzero_ps();
            for (int64_t qi = 0; qi < nb; ++qi) {
                __m512 sc = _mm512_sub_ps(
                    _mm512_cvtepi32_ps(acc[qi]),
                    _mm512_set1_ps(128.0f * static_cast<float>(qsums[qi])));
                sc = _mm512_mul_ps(sc, _mm512_set1_ps(qscales[qi] * a.dot_mul));
                // mul+add (not fmadd) so scores bit-match the scalar path
                sc = _mm512_add_ps(_mm512_mul_ps(sc, scales16), bias16);
                uint16_t m = gmask & _mm512_cmp_ps_mask(
                    sc, _mm512_set1_ps(heapmin[qi]), _CMP_GT_OQ);
                if (!m) continue;
                if (a.mask && a.mask_stride) {
                    const __m128i mb = _mm_loadu_si128(
                        reinterpret_cast<const __m128i*>(
                            a.mask + (q0 + qi) * a.mask_stride + g * 16));
                    m &= _mm_test_epi8_mask(mb, mb);
                    if (!m) continue;
                }
                alignas(64) float svals[16];
                _mm512_store_ps(svals, sc);
                TopK& h = heaps[qi];
                do {
                    const int lane = __builtin_ctz(m);
                    h.push(svals[lane], static_cast<int32_t>(g * 16 + lane));
                    m &= static_cast<uint16_t>(m - 1);
                } while (m);
                if (h.size == a.k) heapmin[qi] = h.s[0];
            }
        }
    }
}

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void knn_i8p_vnni(const KnnPArgs& a) {
    const int64_t ng = (a.n + 15) / 16;
    int8_t* qi8 = static_cast<int8_t*>(
        ::operator new(16 * a.d4 * 4, std::align_val_t(64)));
    // thread count: scale with the scan volume (dpbusd steps) so tiny
    // corpora never pay thread spawn; ES_NATIVE_THREADS pins it
    int64_t nthreads = 1;
    const int64_t work = ng * a.d4;
    if (work >= (64 << 10)) {
        unsigned hc = std::thread::hardware_concurrency();
        nthreads = std::min<int64_t>(hc ? hc : 1, 8);
        nthreads = std::min<int64_t>(nthreads, work / (32 << 10) + 1);
    }
    if (const char* env = std::getenv("ES_NATIVE_THREADS")) {
        const long v = std::strtol(env, nullptr, 10);
        if (v > 0) nthreads = std::min<long>(v, 64);
    }
    nthreads = std::min<int64_t>(nthreads, std::max<int64_t>(ng, 1));

    std::vector<float> hs(static_cast<size_t>(nthreads) * 16 * a.k);
    std::vector<int32_t> hr(static_cast<size_t>(nthreads) * 16 * a.k);
    // hoisted out of the block loop; re-initialized per 16-query block.
    // Threads are (re)spawned per block: the per-block scan is >= ~0.3 ms
    // per worker at the engagement threshold, so spawn cost stays a few
    // percent — a pool would only matter for very large query batches
    std::vector<TopK> heaps(static_cast<size_t>(nthreads) * 16);
    std::vector<float> heapmin(static_cast<size_t>(nthreads) * 16);
    for (int64_t q0 = 0; q0 < a.b; q0 += 16) {
        const int64_t nb = std::min<int64_t>(16, a.b - q0);
        float qscales[16];
        int32_t qsums[16];
        quantize_queries_i8(a.queries + q0 * a.d, nb, a.d, a.d4,
                            qi8, qscales, qsums);
        std::fill(heapmin.begin(), heapmin.end(), -INFINITY);
        for (int64_t t = 0; t < nthreads; ++t)
            for (int64_t qi = 0; qi < nb; ++qi)
                heaps[t * 16 + qi] = TopK{
                    hs.data() + (t * 16 + qi) * a.k,
                    hr.data() + (t * 16 + qi) * a.k, a.k, 0};
        if (nthreads == 1) {
            knn_i8p_vnni_range(a, qi8, qscales, qsums, q0, nb, 0, ng, ng,
                               heaps.data(), heapmin.data());
        } else {
            const int64_t per = (ng + nthreads - 1) / nthreads;
            std::vector<std::thread> workers;
            workers.reserve(static_cast<size_t>(nthreads));
            for (int64_t t = 0; t < nthreads; ++t) {
                const int64_t lo = t * per;
                const int64_t hi = std::min(ng, lo + per);
                if (lo >= hi) break;
                workers.emplace_back([&, t, lo, hi]() {
                    knn_i8p_vnni_range(a, qi8, qscales, qsums, q0, nb,
                                       lo, hi, ng,
                                       heaps.data() + t * 16,
                                       heapmin.data() + t * 16);
                });
            }
            for (auto& w : workers) w.join();
            // ordered merge into thread 0's heaps: TopK's total order on
            // (score, row) makes the result partition-independent
            for (int64_t qi = 0; qi < nb; ++qi) {
                TopK& dst = heaps[qi];
                for (int64_t t = 1; t < nthreads; ++t) {
                    TopK& src = heaps[t * 16 + qi];
                    for (int64_t x = 0; x < src.size; ++x)
                        dst.push(src.s[x], src.r[x]);
                }
            }
        }
        for (int64_t qi = 0; qi < nb; ++qi)
            emit_topk(heaps[qi], a.k,
                      a.out_scores + (q0 + qi) * a.k,
                      a.out_rows + (q0 + qi) * a.k);
    }
    ::operator delete(qi8, std::align_val_t(64));
}
#endif

}  // namespace

extern "C" {

// Batched int8 kNN over a 16-row-interleaved packed corpus (see the layout
// comment above; `packed` is u8 with a +128 offset). scores[b,k] /
// rows[b,k], -inf/-1 padding. queries must be metric-prepped f32; per-row
// arrays padded to ceil(n/16)*16.
void es_knn_i8p_topk(const float* queries, int64_t b, int64_t d,
                     const uint8_t* packed, int64_t n, int64_t d4,
                     const float* row_scales, const float* row_bias,
                     float dot_mul,
                     const uint8_t* mask, int64_t mask_stride, int64_t k,
                     float* out_scores, int32_t* out_rows) {
    KnnPArgs a{queries, b, d, packed, n, d4, row_scales,
               row_bias, dot_mul, mask, mask_stride, k,
               out_scores, out_rows};
#if defined(__x86_64__)
    if (__builtin_cpu_supports("avx512vnni") &&
        __builtin_cpu_supports("avx512bw")) {
        knn_i8p_vnni(a);
        return;
    }
#endif
    knn_i8p_scalar(a);
}

// 1 when es_knn_i8p_topk will take the VNNI path on this host, 0 when it
// falls back to the ~100x-slower scalar loop (the serving cost model prices
// the scan accordingly).
int32_t es_knn_i8p_has_vnni(void) {
#if defined(__x86_64__)
    return __builtin_cpu_supports("avx512vnni") &&
           __builtin_cpu_supports("avx512bw") ? 1 : 0;
#else
    return 0;
#endif
}

// Fused BM25: score[i] = boost * idf * (k1+1) * f / (f + k1*(1-b+b*len/avg))
// (reference formula: LuceneBM25Similarity; queries.py:137 numpy version)
void es_bm25_score(const int32_t* freqs, const float* lengths, int64_t n,
                   float idf, float avg_len, float k1, float b, float boost,
                   float* out) {
    const float scale = boost * idf * (k1 + 1.0f);
    const float one_minus_b = 1.0f - b;
    const float b_over_avg = avg_len > 0.0f ? b / avg_len : 0.0f;
    for (int64_t i = 0; i < n; ++i) {
        const float f = static_cast<float>(freqs[i]);
        const float norm = k1 * (one_minus_b + b_over_avg * lengths[i]);
        out[i] = scale * f / (f + norm);
    }
}

// Galloping intersection of two sorted unique int64 arrays. Writes the
// matching *positions* in a and b (so callers gather scores), returns the
// match count. Gallops from the smaller array like Lucene's
// ConjunctionDISI advance().
int64_t es_intersect_i64(const int64_t* a, int64_t na,
                         const int64_t* b, int64_t nb,
                         int64_t* out_ia, int64_t* out_ib) {
    if (na > nb)  // always gallop through the longer array
        return es_intersect_i64(b, nb, a, na, out_ib, out_ia);
    int64_t count = 0;
    int64_t j = 0;
    for (int64_t i = 0; i < na && j < nb; ++i) {
        const int64_t target = a[i];
        // gallop: double the step until we overshoot, then binary search
        int64_t step = 1;
        int64_t lo = j;
        while (j + step < nb && b[j + step] < target) {
            lo = j + step;
            step <<= 1;
        }
        int64_t hi = std::min(j + step, nb - 1);
        if (b[hi] < target) { j = nb; break; }
        const int64_t* pos = std::lower_bound(b + lo, b + hi + 1, target);
        j = pos - b;
        if (j < nb && b[j] == target) {
            out_ia[count] = i;
            out_ib[count] = j;
            ++count;
            ++j;
        }
    }
    return count;
}

// Union of two sorted unique int64 arrays with score summing (the SHOULD
// accumulation in bool queries). Returns merged length. Output buffers must
// hold na+nb entries. Null score inputs are treated as all-zero.
int64_t es_union_sum_i64(const int64_t* a, const float* sa, int64_t na,
                         const int64_t* b, const float* sb, int64_t nb,
                         int64_t* out_rows, float* out_scores) {
    int64_t i = 0, j = 0, count = 0;
    while (i < na || j < nb) {
        if (j >= nb || (i < na && a[i] < b[j])) {
            out_rows[count] = a[i];
            out_scores[count] = sa ? sa[i] : 0.0f;
            ++i;
        } else if (i >= na || b[j] < a[i]) {
            out_rows[count] = b[j];
            out_scores[count] = sb ? sb[j] : 0.0f;
            ++j;
        } else {
            out_rows[count] = a[i];
            out_scores[count] = (sa ? sa[i] : 0.0f) + (sb ? sb[j] : 0.0f);
            ++i;
            ++j;
        }
        ++count;
    }
    return count;
}

// Partial top-k selection: indices of the k largest scores, ordered by
// (score desc, index asc) — the tie-break SearchPhaseController.mergeTopDocs
// uses (shard/doc order). Min-heap of k entries, one pass, O(n log k).
int64_t es_topk_f32(const float* scores, int64_t n, int64_t k,
                    int32_t* out_idx) {
    if (k <= 0 || n <= 0) return 0;
    if (k > n) k = n;
    // heap entries: (score, idx); `better` orders by (score desc, idx asc),
    // so under std::*_heap the top is the WORST retained element
    struct Entry { float s; int32_t i; };
    auto better = [](const Entry& x, const Entry& y) {
        if (x.s != y.s) return x.s > y.s;
        return x.i < y.i;
    };
    Entry* heap = new Entry[k];
    int64_t size = 0;
    for (int64_t i = 0; i < n; ++i) {
        const float s = scores[i];
        if (size < k) {
            heap[size++] = {s, static_cast<int32_t>(i)};
            std::push_heap(heap, heap + size, better);
        } else if (s > heap[0].s) {
            // ties keep the incumbent: the scan is index-ascending, so the
            // newcomer's larger index loses the (score desc, idx asc) order
            std::pop_heap(heap, heap + k, better);
            heap[k - 1] = {s, static_cast<int32_t>(i)};
            std::push_heap(heap, heap + k, better);
        }
    }
    std::sort_heap(heap, heap + size, better);  // best-first under `better`
    for (int64_t r = 0; r < size; ++r)
        out_idx[r] = heap[r].i;
    delete[] heap;
    return size;
}

}  // extern "C"
