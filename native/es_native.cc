// Native hot-loop kernels for the host-side lexical search path.
//
// The reference leans on Lucene's C-like Java hot loops for postings
// iteration, BM25 scoring, and top-k heaps (SURVEY.md §2.9: "the TPU build
// ... needs a C++ implementation wherever the reference relies on Lucene's
// hot loops: postings decode, BM25 scoring, top-k heaps"). Vector scoring
// runs on the TPU (ops/, parallel/); these kernels cover the scalar,
// branchy, host-side loops where neither numpy vectorization nor XLA is the
// right tool: galloping sorted-set intersection (bool MUST), k-way
// union-with-score-sum (bool SHOULD), fused BM25 term scoring
// (queries.py bm25_scores), and partial top-k selection
// (search/service.py result ranking).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Every function is allocation-free: callers pass numpy-owned buffers.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// Fused BM25: score[i] = boost * idf * (k1+1) * f / (f + k1*(1-b+b*len/avg))
// (reference formula: LuceneBM25Similarity; queries.py:137 numpy version)
void es_bm25_score(const int32_t* freqs, const float* lengths, int64_t n,
                   float idf, float avg_len, float k1, float b, float boost,
                   float* out) {
    const float scale = boost * idf * (k1 + 1.0f);
    const float one_minus_b = 1.0f - b;
    const float b_over_avg = avg_len > 0.0f ? b / avg_len : 0.0f;
    for (int64_t i = 0; i < n; ++i) {
        const float f = static_cast<float>(freqs[i]);
        const float norm = k1 * (one_minus_b + b_over_avg * lengths[i]);
        out[i] = scale * f / (f + norm);
    }
}

// Galloping intersection of two sorted unique int64 arrays. Writes the
// matching *positions* in a and b (so callers gather scores), returns the
// match count. Gallops from the smaller array like Lucene's
// ConjunctionDISI advance().
int64_t es_intersect_i64(const int64_t* a, int64_t na,
                         const int64_t* b, int64_t nb,
                         int64_t* out_ia, int64_t* out_ib) {
    if (na > nb)  // always gallop through the longer array
        return es_intersect_i64(b, nb, a, na, out_ib, out_ia);
    int64_t count = 0;
    int64_t j = 0;
    for (int64_t i = 0; i < na && j < nb; ++i) {
        const int64_t target = a[i];
        // gallop: double the step until we overshoot, then binary search
        int64_t step = 1;
        int64_t lo = j;
        while (j + step < nb && b[j + step] < target) {
            lo = j + step;
            step <<= 1;
        }
        int64_t hi = std::min(j + step, nb - 1);
        if (b[hi] < target) { j = nb; break; }
        const int64_t* pos = std::lower_bound(b + lo, b + hi + 1, target);
        j = pos - b;
        if (j < nb && b[j] == target) {
            out_ia[count] = i;
            out_ib[count] = j;
            ++count;
            ++j;
        }
    }
    return count;
}

// Union of two sorted unique int64 arrays with score summing (the SHOULD
// accumulation in bool queries). Returns merged length. Output buffers must
// hold na+nb entries. Null score inputs are treated as all-zero.
int64_t es_union_sum_i64(const int64_t* a, const float* sa, int64_t na,
                         const int64_t* b, const float* sb, int64_t nb,
                         int64_t* out_rows, float* out_scores) {
    int64_t i = 0, j = 0, count = 0;
    while (i < na || j < nb) {
        if (j >= nb || (i < na && a[i] < b[j])) {
            out_rows[count] = a[i];
            out_scores[count] = sa ? sa[i] : 0.0f;
            ++i;
        } else if (i >= na || b[j] < a[i]) {
            out_rows[count] = b[j];
            out_scores[count] = sb ? sb[j] : 0.0f;
            ++j;
        } else {
            out_rows[count] = a[i];
            out_scores[count] = (sa ? sa[i] : 0.0f) + (sb ? sb[j] : 0.0f);
            ++i;
            ++j;
        }
        ++count;
    }
    return count;
}

// Partial top-k selection: indices of the k largest scores, ordered by
// (score desc, index asc) — the tie-break SearchPhaseController.mergeTopDocs
// uses (shard/doc order). Min-heap of k entries, one pass, O(n log k).
int64_t es_topk_f32(const float* scores, int64_t n, int64_t k,
                    int32_t* out_idx) {
    if (k <= 0 || n <= 0) return 0;
    if (k > n) k = n;
    // heap entries: (score, idx); `better` orders by (score desc, idx asc),
    // so under std::*_heap the top is the WORST retained element
    struct Entry { float s; int32_t i; };
    auto better = [](const Entry& x, const Entry& y) {
        if (x.s != y.s) return x.s > y.s;
        return x.i < y.i;
    };
    Entry* heap = new Entry[k];
    int64_t size = 0;
    for (int64_t i = 0; i < n; ++i) {
        const float s = scores[i];
        if (size < k) {
            heap[size++] = {s, static_cast<int32_t>(i)};
            std::push_heap(heap, heap + size, better);
        } else if (s > heap[0].s) {
            // ties keep the incumbent: the scan is index-ascending, so the
            // newcomer's larger index loses the (score desc, idx asc) order
            std::pop_heap(heap, heap + k, better);
            heap[k - 1] = {s, static_cast<int32_t>(i)};
            std::push_heap(heap, heap + k, better);
        }
    }
    std::sort_heap(heap, heap + size, better);  // best-first under `better`
    for (int64_t r = 0; r < size; ++r)
        out_idx[r] = heap[r].i;
    delete[] heap;
    return size;
}

}  // extern "C"
