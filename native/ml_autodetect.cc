// ml_autodetect — native anomaly-detection sidecar process.
//
// TPU-native re-design of the reference's ML C++ processes (external repo
// elastic/ml-cpp, spawned by bootstrap/Spawner.java:42 and managed via
// x-pack/plugin/ml/.../process/NativeController.java + ProcessPipes.java,
// results parsed from JSON in IndexingStateProcessor.java).  Same role:
// a per-job native process that receives a stream of time-ordered records
// and emits bucketed anomaly results — but the protocol here is a simple
// length-prefixed JSON framing over stdin/stdout (SURVEY.md §2.9: "a C++
// sidecar speaking length-prefixed JSON over pipes/UDS").
//
// Frame format (both directions): 4-byte big-endian payload length + UTF-8
// JSON payload.
//
// Inbound frame types:
//   {"type":"config", "job": {...job config...}, "state": {...optional...}}
//   {"type":"record", "time": <epoch seconds>, "fields": {name: value, ...}}
//   {"type":"flush", "id": "<flush id>"}           — close current bucket, ack
//   {"type":"persist"}                             — emit model state frame
//   {"type":"quit"}                                — finalize + exit
//
// Outbound frame types:
//   {"type":"bucket", ...}   {"type":"record", ...}   {"type":"flush_ack", ...}
//   {"type":"state", "state": {...}}   {"type":"error", "message": "..."}
//
// Analysis semantics (re-designed, not ported): each detector keeps an
// online Gaussian baseline (Welford mean/M2) over per-bucket values, split
// by the detector's partition/by field values.  On bucket close the actual
// value's two-sided (or one-sided for low_/high_ variants) normal tail
// probability becomes record_score = min(100, -10*log10(p)).  `rare`
// detectors model the categorical frequency of the by_field instead.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// Minimal JSON value + parser + writer (no external deps).
// ---------------------------------------------------------------------------

struct JValue;
using JObject = std::map<std::string, JValue>;
using JArray = std::vector<JValue>;

struct JValue {
  enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  double num = 0;
  std::string str;
  std::shared_ptr<JArray> arr;
  std::shared_ptr<JObject> obj;

  JValue() = default;
  static JValue of(double d) { JValue v; v.kind = NUM; v.num = d; return v; }
  static JValue of(bool x) { JValue v; v.kind = BOOL; v.b = x; return v; }
  static JValue of(const std::string& s) { JValue v; v.kind = STR; v.str = s; return v; }
  static JValue of(const char* s) { return of(std::string(s)); }
  static JValue object() { JValue v; v.kind = OBJ; v.obj = std::make_shared<JObject>(); return v; }
  static JValue array() { JValue v; v.kind = ARR; v.arr = std::make_shared<JArray>(); return v; }

  bool is_num() const { return kind == NUM; }
  bool is_str() const { return kind == STR; }
  bool is_obj() const { return kind == OBJ; }
  const JValue* get(const std::string& k) const {
    if (kind != OBJ) return nullptr;
    auto it = obj->find(k);
    return it == obj->end() ? nullptr : &it->second;
  }
  double num_or(double d) const { return kind == NUM ? num : d; }
  std::string str_or(const std::string& d) const { return kind == STR ? str : d; }
};

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p; }
  bool lit(const char* s) {
    size_t n = strlen(s);
    if (size_t(end - p) < n || strncmp(p, s, n) != 0) { ok = false; return false; }
    p += n;
    return true;
  }

  JValue parse() { ws(); JValue v = value(); ws(); return v; }

  JValue value() {
    ws();
    if (p >= end) { ok = false; return JValue(); }
    switch (*p) {
      case '{': return object();
      case '[': return array();
      case '"': { JValue v; v.kind = JValue::STR; v.str = string(); return v; }
      case 't': lit("true"); return JValue::of(true);
      case 'f': lit("false"); return JValue::of(false);
      case 'n': lit("null"); return JValue();
      default: return number();
    }
  }

  JValue object() {
    JValue v = JValue::object();
    ++p;  // {
    ws();
    if (p < end && *p == '}') { ++p; return v; }
    while (ok && p < end) {
      ws();
      if (*p != '"') { ok = false; break; }
      std::string key = string();
      ws();
      if (p >= end || *p != ':') { ok = false; break; }
      ++p;
      (*v.obj)[key] = value();
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      ok = false;
      break;
    }
    return v;
  }

  JValue array() {
    JValue v = JValue::array();
    ++p;  // [
    ws();
    if (p < end && *p == ']') { ++p; return v; }
    while (ok && p < end) {
      v.arr->push_back(value());
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; break; }
      ok = false;
      break;
    }
    return v;
  }

  std::string string() {
    std::string out;
    ++p;  // opening quote
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {
            if (end - p >= 5) {
              unsigned cp = 0;
              sscanf(p + 1, "%4x", &cp);
              p += 4;
              // encode UTF-8 (BMP only; surrogate pairs pass through raw)
              if (cp < 0x80) out += char(cp);
              else if (cp < 0x800) {
                out += char(0xC0 | (cp >> 6));
                out += char(0x80 | (cp & 0x3F));
              } else {
                out += char(0xE0 | (cp >> 12));
                out += char(0x80 | ((cp >> 6) & 0x3F));
                out += char(0x80 | (cp & 0x3F));
              }
            }
            break;
          }
          default: out += *p;
        }
      } else {
        out += *p;
      }
      ++p;
    }
    if (p < end) ++p;  // closing quote
    else ok = false;
    return out;
  }

  JValue number() {
    char* np = nullptr;
    double d = strtod(p, &np);
    if (np == p) { ok = false; return JValue(); }
    p = np;
    return JValue::of(d);
  }
};

static void write_json(const JValue& v, std::string& out) {
  char buf[32];
  switch (v.kind) {
    case JValue::NUL: out += "null"; break;
    case JValue::BOOL: out += v.b ? "true" : "false"; break;
    case JValue::NUM: {
      if (std::isfinite(v.num) && v.num == (int64_t)v.num &&
          std::fabs(v.num) < 9e15) {
        snprintf(buf, sizeof buf, "%lld", (long long)v.num);
      } else if (std::isfinite(v.num)) {
        snprintf(buf, sizeof buf, "%.12g", v.num);
      } else {
        snprintf(buf, sizeof buf, "null");
      }
      out += buf;
      break;
    }
    case JValue::STR: {
      out += '"';
      for (char c : v.str) {
        switch (c) {
          case '"': out += "\\\""; break;
          case '\\': out += "\\\\"; break;
          case '\n': out += "\\n"; break;
          case '\t': out += "\\t"; break;
          case '\r': out += "\\r"; break;
          default:
            if ((unsigned char)c < 0x20) {
              snprintf(buf, sizeof buf, "\\u%04x", c);
              out += buf;
            } else {
              out += c;
            }
        }
      }
      out += '"';
      break;
    }
    case JValue::ARR: {
      out += '[';
      bool first = true;
      for (const auto& e : *v.arr) {
        if (!first) out += ',';
        first = false;
        write_json(e, out);
      }
      out += ']';
      break;
    }
    case JValue::OBJ: {
      out += '{';
      bool first = true;
      for (const auto& kv : *v.obj) {
        if (!first) out += ',';
        first = false;
        write_json(JValue::of(kv.first), out);
        out += ':';
        write_json(kv.second, out);
      }
      out += '}';
      break;
    }
  }
}

// ---------------------------------------------------------------------------
// Framing
// ---------------------------------------------------------------------------

static bool read_frame(std::string& payload) {
  unsigned char hdr[4];
  if (fread(hdr, 1, 4, stdin) != 4) return false;
  uint32_t len = (uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                 (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]);
  if (len > (64u << 20)) return false;  // 64 MB sanity cap
  payload.resize(len);
  return len == 0 || fread(&payload[0], 1, len, stdin) == len;
}

static void write_frame(const JValue& v) {
  std::string payload;
  write_json(v, payload);
  unsigned char hdr[4] = {
      (unsigned char)(payload.size() >> 24), (unsigned char)(payload.size() >> 16),
      (unsigned char)(payload.size() >> 8), (unsigned char)payload.size()};
  fwrite(hdr, 1, 4, stdout);
  fwrite(payload.data(), 1, payload.size(), stdout);
  fflush(stdout);
}

// ---------------------------------------------------------------------------
// Detector models
// ---------------------------------------------------------------------------

// Online Gaussian baseline over per-bucket metric values (Welford).
struct MetricModel {
  double n = 0, mean = 0, m2 = 0;

  void add(double x) {
    n += 1;
    double d = x - mean;
    mean += d / n;
    m2 += d * (x - mean);
  }
  double variance() const { return n > 1 ? m2 / (n - 1) : 0; }
  // Two-sided normal tail probability of seeing a value this far from mean.
  double probability(double x, int side) const {
    if (n < 3) return 1.0;  // not enough history to call anything anomalous
    double sd = std::sqrt(variance());
    if (sd < 1e-9) sd = std::fabs(mean) * 0.01 + 1e-9;
    double z = (x - mean) / sd;
    if (side < 0 && z > 0) return 1.0;   // low_* detector: high values normal
    if (side > 0 && z < 0) return 1.0;   // high_* detector
    double p = std::erfc(std::fabs(z) / std::sqrt(2.0));
    return side == 0 ? p : p / 2;
  }
};

// Categorical frequency model for `rare`: how unusual is this by-value?
struct RareModel {
  std::map<std::string, double> counts;
  double total = 0;

  void add(const std::string& v, double c) { counts[v] += c; total += c; }
  double probability(const std::string& v) const {
    if (total < 10) return 1.0;
    auto it = counts.find(v);
    double c = it == counts.end() ? 0 : it->second;
    return (c + 1) / (total + 1);
  }
};

struct Detector {
  std::string function;     // count/low_count/high_count/mean/min/max/sum/metric/rare/distinct_count
  std::string field_name;
  std::string by_field;
  std::string partition_field;
  int side = 0;  // -1 low_*, +1 high_*, 0 two-sided

  // entity key ("partition\x1eby") -> model
  std::map<std::string, MetricModel> models;
  std::map<std::string, RareModel> rare_models;
};

// Per-bucket accumulator for one (detector, entity).
struct BucketAgg {
  double count = 0, sum = 0;
  double min = 1e300, max = -1e300;
  std::map<std::string, double> by_counts;  // for rare/distinct_count
};

struct Autodetect {
  std::string job_id;
  double bucket_span = 300;
  std::string time_field = "time";
  std::vector<Detector> detectors;

  double bucket_start = -1;          // current open bucket start, -1 = none
  double latest_time = -1;
  // (detector idx, entity key) -> accumulator
  std::map<std::pair<int, std::string>, BucketAgg> accum;

  void configure(const JValue& job) {
    if (const JValue* id = job.get("job_id")) job_id = id->str_or(job_id);
    if (const JValue* dd = job.get("data_description")) {
      if (const JValue* tf = dd->get("time_field")) time_field = tf->str_or(time_field);
    }
    const JValue* ac = job.get("analysis_config");
    if (!ac || !ac->is_obj()) return;
    if (const JValue* bs = ac->get("bucket_span")) {
      if (bs->is_num()) bucket_span = bs->num;
      else if (bs->is_str()) bucket_span = parse_span(bs->str);
    }
    if (const JValue* dets = ac->get("detectors")) {
      if (dets->kind == JValue::ARR) {
        for (const auto& d : *dets->arr) {
          Detector det;
          if (const JValue* f = d.get("function")) det.function = f->str_or("count");
          if (const JValue* f = d.get("field_name")) det.field_name = f->str_or("");
          if (const JValue* f = d.get("by_field_name")) det.by_field = f->str_or("");
          if (const JValue* f = d.get("partition_field_name"))
            det.partition_field = f->str_or("");
          if (det.function.rfind("low_", 0) == 0) {
            det.side = -1;
            det.function = det.function.substr(4);
          } else if (det.function.rfind("high_", 0) == 0) {
            det.side = 1;
            det.function = det.function.substr(5);
          }
          detectors.push_back(std::move(det));
        }
      }
    }
    if (detectors.empty()) detectors.push_back(Detector{"count"});
  }

  static double parse_span(const std::string& s) {
    char* endp = nullptr;
    double v = strtod(s.c_str(), &endp);
    if (endp && *endp) {
      switch (*endp) {
        case 's': return v;
        case 'm': return v * 60;
        case 'h': return v * 3600;
        case 'd': return v * 86400;
      }
    }
    return v > 0 ? v : 300;
  }

  // --- state persist / restore --------------------------------------------

  JValue state_json() const {
    JValue st = JValue::object();
    JValue dets = JValue::array();
    for (const auto& det : detectors) {
      JValue d = JValue::object();
      JValue ms = JValue::object();
      for (const auto& kv : det.models) {
        JValue m = JValue::array();
        m.arr->push_back(JValue::of(kv.second.n));
        m.arr->push_back(JValue::of(kv.second.mean));
        m.arr->push_back(JValue::of(kv.second.m2));
        (*ms.obj)[kv.first] = m;
      }
      (*d.obj)["models"] = ms;
      JValue rs = JValue::object();
      for (const auto& kv : det.rare_models) {
        JValue r = JValue::object();
        for (const auto& ckv : kv.second.counts)
          (*r.obj)[ckv.first] = JValue::of(ckv.second);
        (*rs.obj)[kv.first] = r;
      }
      (*d.obj)["rare"] = rs;
      dets.arr->push_back(d);
    }
    (*st.obj)["detectors"] = dets;
    (*st.obj)["latest_time"] = JValue::of(latest_time);
    return st;
  }

  void restore_state(const JValue& st) {
    const JValue* dets = st.get("detectors");
    if (!dets || dets->kind != JValue::ARR) return;
    for (size_t i = 0; i < dets->arr->size() && i < detectors.size(); ++i) {
      const JValue& d = (*dets->arr)[i];
      if (const JValue* ms = d.get("models")) {
        if (ms->is_obj()) {
          for (const auto& kv : *ms->obj) {
            if (kv.second.kind == JValue::ARR && kv.second.arr->size() == 3) {
              MetricModel m;
              m.n = (*kv.second.arr)[0].num_or(0);
              m.mean = (*kv.second.arr)[1].num_or(0);
              m.m2 = (*kv.second.arr)[2].num_or(0);
              detectors[i].models[kv.first] = m;
            }
          }
        }
      }
      if (const JValue* rs = d.get("rare")) {
        if (rs->is_obj()) {
          for (const auto& kv : *rs->obj) {
            RareModel r;
            if (kv.second.is_obj()) {
              for (const auto& ckv : *kv.second.obj) {
                r.counts[ckv.first] = ckv.second.num_or(0);
                r.total += ckv.second.num_or(0);
              }
            }
            detectors[i].rare_models[kv.first] = r;
          }
        }
      }
    }
    if (const JValue* lt = st.get("latest_time")) latest_time = lt->num_or(-1);
  }

  // --- record ingestion ----------------------------------------------------

  static std::string field_str(const JValue& fields, const std::string& name) {
    const JValue* v = fields.get(name);
    if (!v) return "";
    if (v->is_str()) return v->str;
    if (v->is_num()) {
      std::string out;
      write_json(*v, out);
      return out;
    }
    return "";
  }

  void add_record(double t, const JValue& fields) {
    if (t < latest_time) return;  // out-of-order: dropped (host counts these)
    // records for a bucket already finalized by flush are too old to score
    if (bucket_start >= 0 && t < bucket_start) return;
    latest_time = t;
    double bstart = std::floor(t / bucket_span) * bucket_span;
    if (bucket_start < 0) bucket_start = bstart;
    while (bstart >= bucket_start + bucket_span) close_bucket();

    for (size_t i = 0; i < detectors.size(); ++i) {
      Detector& det = detectors[i];
      std::string entity = entity_key(det, fields);
      BucketAgg& agg = accum[{int(i), entity}];
      agg.count += 1;
      if (!det.field_name.empty()) {
        const JValue* v = fields.get(det.field_name);
        if (v && v->is_num()) {
          agg.sum += v->num;
          if (v->num < agg.min) agg.min = v->num;
          if (v->num > agg.max) agg.max = v->num;
        } else {
          agg.count -= 1;  // missing metric field: record doesn't count
        }
      }
      if (!det.by_field.empty() &&
          (det.function == "rare" || det.function == "distinct_count")) {
        std::string bv = field_str(fields, det.by_field);
        if (!bv.empty()) agg.by_counts[bv] += 1;
      }
    }
  }

  static std::string entity_key(const Detector& det, const JValue& fields) {
    std::string key;
    if (!det.partition_field.empty()) key += field_str(fields, det.partition_field);
    key += '\x1e';
    // rare/distinct_count model the by-distribution itself, so the by value
    // is data, not identity
    if (!det.by_field.empty() && det.function != "rare" &&
        det.function != "distinct_count")
      key += field_str(fields, det.by_field);
    return key;
  }

  static double score_from_probability(double p) {
    if (p >= 1) return 0;
    if (p < 1e-308) p = 1e-308;
    double s = -10 * std::log10(p) - 13;  // ~p<0.05 before any score
    if (s < 0) s = 0;
    if (s > 100) s = 100;
    return s;
  }

  void close_bucket() {
    if (bucket_start < 0) return;
    double max_record_score = 0;
    double total_anomaly = 0;
    JArray records;

    for (size_t i = 0; i < detectors.size(); ++i) {
      Detector& det = detectors[i];
      // collect entities seen this bucket for this detector
      for (auto it = accum.begin(); it != accum.end(); ++it) {
        if (it->first.first != int(i)) continue;
        const std::string& entity = it->first.second;
        BucketAgg& agg = it->second;

        if (det.function == "rare") {
          RareModel& rm = det.rare_models[entity];
          for (const auto& bv : agg.by_counts) {
            double p = rm.probability(bv.first);
            double score = score_from_probability(p);
            if (score > 0.1)
              emit_record(records, det, entity, bv.first, score, p, bv.second, 0);
            if (score > max_record_score) max_record_score = score;
            total_anomaly += score;
          }
          for (const auto& bv : agg.by_counts) rm.add(bv.first, bv.second);
          continue;
        }

        double actual;
        if (det.function == "count") actual = agg.count;
        else if (det.function == "sum") actual = agg.sum;
        else if (det.function == "min") actual = agg.count > 0 ? agg.min : 0;
        else if (det.function == "max") actual = agg.count > 0 ? agg.max : 0;
        else if (det.function == "distinct_count") actual = double(agg.by_counts.size());
        else actual = agg.count > 0 ? agg.sum / agg.count : 0;  // mean/metric

        MetricModel& m = det.models[entity];
        double p = m.probability(actual, det.side);
        double score = score_from_probability(p);
        if (score > 0.1)
          emit_record(records, det, entity, "", score, p, actual, m.mean);
        if (score > max_record_score) max_record_score = score;
        total_anomaly += score;
        m.add(actual);
      }
    }

    // bucket result
    JValue b = JValue::object();
    (*b.obj)["type"] = JValue::of("bucket");
    (*b.obj)["job_id"] = JValue::of(job_id);
    (*b.obj)["timestamp"] = JValue::of(bucket_start * 1000);
    (*b.obj)["bucket_span"] = JValue::of(bucket_span);
    (*b.obj)["anomaly_score"] = JValue::of(max_record_score);
    (*b.obj)["initial_anomaly_score"] = JValue::of(max_record_score);
    (*b.obj)["event_count"] = JValue::of(total_event_count());
    (*b.obj)["is_interim"] = JValue::of(false);
    (*b.obj)["result_type"] = JValue::of("bucket");
    write_frame(b);
    for (auto& r : records) write_frame(r);

    accum.clear();
    bucket_start += bucket_span;
  }

  double total_event_count() const {
    double n = 0;
    for (const auto& kv : accum)
      if (kv.first.first == 0) n += kv.second.count;
    return n;
  }

  void emit_record(JArray& records, const Detector& det, const std::string& entity,
                   const std::string& by_value, double score, double prob,
                   double actual, double typical) {
    JValue r = JValue::object();
    (*r.obj)["type"] = JValue::of("record");
    (*r.obj)["job_id"] = JValue::of(job_id);
    (*r.obj)["result_type"] = JValue::of("record");
    (*r.obj)["timestamp"] = JValue::of(bucket_start * 1000);
    (*r.obj)["bucket_span"] = JValue::of(bucket_span);
    (*r.obj)["record_score"] = JValue::of(score);
    (*r.obj)["initial_record_score"] = JValue::of(score);
    (*r.obj)["probability"] = JValue::of(prob);
    std::string fname = (det.side < 0 ? "low_" : det.side > 0 ? "high_" : "");
    (*r.obj)["function"] = JValue::of(fname + det.function);
    if (!det.field_name.empty())
      (*r.obj)["field_name"] = JValue::of(det.field_name);
    size_t sep = entity.find('\x1e');
    std::string part = sep == std::string::npos ? "" : entity.substr(0, sep);
    std::string byv = by_value.empty()
                          ? (sep == std::string::npos ? "" : entity.substr(sep + 1))
                          : by_value;
    if (!det.partition_field.empty()) {
      (*r.obj)["partition_field_name"] = JValue::of(det.partition_field);
      (*r.obj)["partition_field_value"] = JValue::of(part);
    }
    if (!det.by_field.empty()) {
      (*r.obj)["by_field_name"] = JValue::of(det.by_field);
      (*r.obj)["by_field_value"] = JValue::of(byv);
    }
    JValue act = JValue::array();
    act.arr->push_back(JValue::of(actual));
    (*r.obj)["actual"] = act;
    if (det.function != "rare") {
      JValue typ = JValue::array();
      typ.arr->push_back(JValue::of(typical));
      (*r.obj)["typical"] = typ;
    }
    (*r.obj)["is_interim"] = JValue::of(false);
    records.push_back(r);
  }
};

// ---------------------------------------------------------------------------

int main() {
  Autodetect ad;
  std::string payload;
  bool configured = false;

  while (read_frame(payload)) {
    JParser parser(payload);
    JValue msg = parser.parse();
    if (!parser.ok || !msg.is_obj()) {
      JValue err = JValue::object();
      (*err.obj)["type"] = JValue::of("error");
      (*err.obj)["message"] = JValue::of("malformed frame");
      write_frame(err);
      continue;
    }
    std::string type = msg.get("type") ? msg.get("type")->str_or("") : "";

    if (type == "config") {
      if (const JValue* job = msg.get("job")) ad.configure(*job);
      if (const JValue* st = msg.get("state")) ad.restore_state(*st);
      configured = true;
    } else if (type == "record") {
      if (!configured) continue;
      const JValue* t = msg.get("time");
      const JValue* fields = msg.get("fields");
      if (t && t->is_num() && fields && fields->is_obj())
        ad.add_record(t->num, *fields);
    } else if (type == "flush") {
      if (!ad.accum.empty()) ad.close_bucket();
      JValue ack = JValue::object();
      (*ack.obj)["type"] = JValue::of("flush_ack");
      (*ack.obj)["id"] = msg.get("id") ? *msg.get("id") : JValue::of("");
      (*ack.obj)["last_finalized_bucket_end"] =
          JValue::of(ad.bucket_start > 0 ? ad.bucket_start * 1000 : 0);
      write_frame(ack);
    } else if (type == "persist") {
      JValue st = JValue::object();
      (*st.obj)["type"] = JValue::of("state");
      (*st.obj)["state"] = ad.state_json();
      write_frame(st);
    } else if (type == "quit") {
      if (!ad.accum.empty()) ad.close_bucket();
      break;
    }
  }
  return 0;
}
